"""Hardware ceilings + XLA cost extraction for the profiling layer.

The roofline model needs two kinds of numbers:

- **What the executable does** — flops and bytes accessed, from XLA's own
  ``compiled.cost_analysis()``. :func:`extract_cost` normalizes the two
  shapes jax returns it in (a dict, or a singleton list of dicts) into an
  :class:`ExecutableCost`, captured ONCE at compile time (``_aot/cache.py``)
  and persisted in the artifact header so an AOT disk hit — which skips
  compilation entirely — still recovers the cost without re-lowering.
- **What the hardware could do** — peak flops and HBM bandwidth, the
  denominators of the MFU and roofline gauges. :func:`get_ceilings`
  resolves them in priority order: env overrides (``TM_TPU_PEAK_FLOPS``,
  ``TM_TPU_HBM_BW``), a measured-ceilings JSON checked in from
  ``tools/fid_mfu_experiment.py --json`` (``TM_TPU_CEILINGS_JSON`` or the
  default ``_analysis/roofline_ceilings.json``), then the TPU v5e paper
  constants the bench suite uses.

With cost and ceilings in hand the gauges are closed-form::

    mfu      = flops / (seconds * peak_flops)
    ceiling  = min(1, arithmetic_intensity * hbm_bw / peak_flops)

where ``arithmetic_intensity = flops / bytes_accessed``. ``ceiling`` is the
roofline bound on MFU for a memory-bound kernel: achieved/ceiling is the
fraction of the *attainable* (not absolute) peak, which is the number a
kernel-optimization effort actually moves (ROADMAP item 5).

This module must stay import-light (no jax, no numpy): the ledger imports
it at module scope, and the ledger is imported by ``metric.py``.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Dict, Optional

__all__ = [
    "ExecutableCost",
    "Ceilings",
    "extract_cost",
    "get_ceilings",
    "set_ceilings",
    "load_measured_ceilings",
    "CEILINGS_PATH",
    "DEFAULT_PEAK_FLOPS",
    "DEFAULT_HBM_BYTES_PER_S",
]

# TPU v5e bf16 peak + HBM bandwidth — the same constants bench.py's roofline
# sections use (kept in sync by tests/unittests/observability/test_profiling.py)
DEFAULT_PEAK_FLOPS = 394e12
DEFAULT_HBM_BYTES_PER_S = 819e9

CEILINGS_PATH = Path(__file__).resolve().parents[1] / "_analysis" / "roofline_ceilings.json"
_CEILINGS_VERSION = 1


@dataclass(frozen=True)
class ExecutableCost:
    """XLA's static cost claim for ONE compiled executable."""

    flops: float
    bytes_accessed: float

    @property
    def arithmetic_intensity(self) -> float:
        """Flops per byte of memory traffic (0 when bytes are unknown)."""
        return self.flops / self.bytes_accessed if self.bytes_accessed > 0 else 0.0

    def roofline_ceiling(self, ceilings: "Ceilings") -> float:
        """Attainable MFU under the roofline: memory-bound kernels cap below 1."""
        if self.bytes_accessed <= 0 or ceilings.peak_flops <= 0:
            return 1.0
        return min(1.0, self.arithmetic_intensity * ceilings.hbm_bytes_per_s / ceilings.peak_flops)

    def mfu(self, seconds: float, ceilings: "Ceilings") -> float:
        """Achieved fraction of absolute peak for one step of ``seconds``."""
        if seconds <= 0 or ceilings.peak_flops <= 0:
            return 0.0
        return self.flops / (seconds * ceilings.peak_flops)

    def to_json(self) -> Dict[str, float]:
        return {"flops": self.flops, "bytes_accessed": self.bytes_accessed}


@dataclass(frozen=True)
class Ceilings:
    """Hardware performance ceilings the gauges divide by."""

    peak_flops: float
    hbm_bytes_per_s: float
    source: str  # "env" | "measured:<path>" | "default"

    def to_json(self) -> Dict[str, Any]:
        return {
            "peak_flops": self.peak_flops,
            "hbm_bytes_per_s": self.hbm_bytes_per_s,
            "source": self.source,
        }


def extract_cost(compiled: Any) -> Optional[ExecutableCost]:
    """Normalize ``compiled.cost_analysis()`` into an :class:`ExecutableCost`.

    Returns ``None`` when the backend exposes no cost analysis (older
    runtimes, some CPU builds) or the call fails — profiling then degrades
    to pure wall-time accounting for that executable, never to an error on
    the compile path.
    """
    try:
        analysis = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 - any backend failure degrades to no-cost
        return None
    # jax has returned both a bare dict and a one-element list of dicts
    # across versions; accept either
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return None
    try:
        flops = float(analysis.get("flops", 0.0) or 0.0)
        bytes_accessed = float(analysis.get("bytes accessed", 0.0) or 0.0)
    except (TypeError, ValueError):
        return None
    if flops <= 0.0 and bytes_accessed <= 0.0:
        return None
    return ExecutableCost(flops=flops, bytes_accessed=bytes_accessed)


def load_measured_ceilings(path: Optional[Path] = None) -> Optional[Ceilings]:
    """Ceilings from a checked-in ``fid_mfu_experiment.py --json`` artifact.

    Returns ``None`` when the file is absent or unreadable — measured
    ceilings are an upgrade, never a requirement.
    """
    target = Path(path) if path is not None else CEILINGS_PATH
    try:
        blob = json.loads(target.read_text(encoding="utf-8"))
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        return None
    if not isinstance(blob, dict) or blob.get("version") != _CEILINGS_VERSION:
        return None
    try:
        return Ceilings(
            peak_flops=float(blob["peak_flops"]),
            hbm_bytes_per_s=float(blob["hbm_bytes_per_s"]),
            source=f"measured:{target.name}",
        )
    except (KeyError, TypeError, ValueError):
        return None


# process-wide resolved ceilings; a list so set_ceilings swaps atomically
# under the GIL without a lock (single small-object assignment)
_ACTIVE: list = []


def _resolve() -> Ceilings:
    env_peak = os.environ.get("TM_TPU_PEAK_FLOPS")
    env_bw = os.environ.get("TM_TPU_HBM_BW")
    if env_peak or env_bw:
        try:
            return Ceilings(
                peak_flops=float(env_peak) if env_peak else DEFAULT_PEAK_FLOPS,
                hbm_bytes_per_s=float(env_bw) if env_bw else DEFAULT_HBM_BYTES_PER_S,
                source="env",
            )
        except ValueError:
            pass  # malformed override falls through to measured/default
    measured_path = os.environ.get("TM_TPU_CEILINGS_JSON")
    measured = load_measured_ceilings(Path(measured_path) if measured_path else None)
    if measured is not None:
        return measured
    return Ceilings(
        peak_flops=DEFAULT_PEAK_FLOPS,
        hbm_bytes_per_s=DEFAULT_HBM_BYTES_PER_S,
        source="default",
    )


def get_ceilings() -> Ceilings:
    """The active hardware ceilings (env > measured JSON > v5e defaults)."""
    if not _ACTIVE:
        _ACTIVE.append(_resolve())
    return _ACTIVE[0]


def set_ceilings(ceilings: Optional[Ceilings]) -> None:
    """Override the active ceilings (``None`` re-resolves from env/JSON)."""
    _ACTIVE.clear()
    if ceilings is not None:
        _ACTIVE.append(ceilings)
