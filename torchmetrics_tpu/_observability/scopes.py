"""Trace-attribution scopes for XLA/host profilers.

Two complementary mechanisms:

- :func:`named_scope` — ``jax.named_scope``: attaches a name to every HLO op
  emitted while the scope is open, so a *device* profile (XLA trace) groups
  time under ``ClassName.update`` / ``ClassName.compute`` instead of a soup
  of anonymous fusions. Zero runtime cost after compilation (the names live
  in compile-time metadata), so the traced update/compute bodies open it
  unconditionally.
- :func:`annotation` — ``jax.profiler.TraceAnnotation``: a *host* profiler
  range (visible in ``jax.profiler.trace`` / TensorBoard) around eager
  update bodies, compiled dispatches, and sync. It costs a context entry
  per call, so instrumented sites open it only while telemetry is enabled
  (``state.OBS.profile_scopes`` additionally gates it for
  counters-without-profiling deployments).

Both degrade to ``nullcontext`` on jax versions lacking the API.
"""

from __future__ import annotations

from contextlib import nullcontext
from typing import Any

from torchmetrics_tpu._observability.state import OBS

__all__ = ["named_scope", "annotation", "profiling_scopes_active"]

try:  # pragma: no cover - version portability
    from jax import named_scope as _named_scope
except ImportError:  # pragma: no cover
    _named_scope = None

try:  # pragma: no cover - version portability
    from jax.profiler import TraceAnnotation as _TraceAnnotation
except ImportError:  # pragma: no cover
    _TraceAnnotation = None


def named_scope(name: str) -> Any:
    """HLO name scope (device-profile attribution); nullcontext fallback."""
    if _named_scope is None:
        return nullcontext()
    return _named_scope(name)


def annotation(name: str) -> Any:
    """Host profiler range; callers gate on :func:`profiling_scopes_active`."""
    if _TraceAnnotation is None:
        return nullcontext()
    return _TraceAnnotation(name)


def profiling_scopes_active() -> bool:
    return OBS.enabled and OBS.profile_scopes


def set_profile_scopes(flag: bool) -> None:
    """Enable/disable host profiler annotations independently of counters."""
    OBS.profile_scopes = bool(flag)
