"""Declarative SLOs: latency targets + error budgets over existing signals.

The telemetry layer already collects everything an availability story needs
— latency reservoirs per op and exact counter families per class — but
"what do the numbers *mean*" lived in people's heads. This module makes the
objectives declarative and the judgment mechanical:

- :class:`SLO` — one objective, in one of two shapes:

  * **latency**: "``objective`` of ``op`` calls complete within
    ``threshold_ms``" — evaluated over the pooled retained reservoir
    windows of every live instance (the recent-behavior window, exactly
    what a readiness probe should judge);
  * **error rate**: "at most ``1 - objective`` of ``total`` operations land
    in ``bad`` counters" — evaluated over a sliding wall-clock window of
    counter *deltas* (checkpointed per evaluation), so a burst burns the
    budget and then ages out instead of poisoning the lifetime ratio.

- **burn rate** — the classic error-budget consumption speed:
  ``burn = bad_fraction / (1 - objective)``. 1.0 means the budget is being
  consumed exactly at the sustainable rate; 14.4 is the canonical
  page-immediately threshold (a 30-day budget gone in ~2 days).

- :func:`health_report` / :meth:`SloTracker.health_report` — one snapshot
  (``healthy`` bool + per-SLO compliance/burn/status) suitable for a
  readiness probe; ``to_json()`` is guaranteed serializable at the source.

Nothing here touches a hot path: evaluation reads the registry aggregate on
demand (scrape-rate, not stream-rate).
"""

from __future__ import annotations

import json
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from torchmetrics_tpu._analysis.locksan import SAN as _SAN
from torchmetrics_tpu._analysis.locksan import check_access as _san_check
from torchmetrics_tpu._analysis.locksan import new_lock as _san_lock
from torchmetrics_tpu._observability.reservoir import nearest_rank
from torchmetrics_tpu._observability.state import OBS
from torchmetrics_tpu._observability.telemetry import REGISTRY, _split_key

__all__ = [
    "SLO",
    "SloStatus",
    "SloTracker",
    "HealthReport",
    "DEFAULT_SLOS",
    "set_slos",
    "health_report",
    "FAST_BURN",
]

# burn rate above which the budget math says "page now, not at review time":
# at 14.4x a 30-day budget is gone in ~2 days (the SRE-workbook constant)
FAST_BURN = 14.4

# Checkpoint-count ceiling per tracker; interior thinning kicks in above it.
_MAX_CHECKPOINTS = 256


@dataclass(frozen=True)
class SLO:
    """One declarative objective over the telemetry the runtime already has.

    Exactly one mode must be configured:

    - latency: set ``op`` + ``threshold_ms`` (reservoir-backed);
    - error rate: set ``bad`` (+ optionally ``total``) counter families.

    ``objective`` is the good fraction (0.99 = "99% of calls good");
    ``window_s`` bounds the error-rate budget window (checkpointed counter
    deltas older than this age out of the burn computation).
    """

    name: str
    objective: float = 0.99
    # latency mode
    op: Optional[str] = None
    threshold_ms: Optional[float] = None
    # error-rate mode: counter FAMILY names (labels are summed away)
    bad: Tuple[str, ...] = ()
    total: Tuple[str, ...] = ("update_calls",)
    window_s: float = 300.0

    def __post_init__(self) -> None:
        if not (0.0 < self.objective < 1.0):
            raise ValueError(f"`objective` must be in (0, 1), got {self.objective!r}")
        latency_mode = self.op is not None or self.threshold_ms is not None
        error_mode = bool(self.bad)
        if latency_mode == error_mode:
            raise ValueError(
                f"SLO {self.name!r} must configure exactly one mode: latency"
                " (op + threshold_ms) or error rate (bad counter families)"
            )
        if latency_mode and (self.op is None or self.threshold_ms is None or self.threshold_ms <= 0):
            raise ValueError(f"latency SLO {self.name!r} needs both `op` and a positive `threshold_ms`")
        if self.window_s <= 0:
            raise ValueError(f"`window_s` must be positive, got {self.window_s!r}")

    @property
    def kind(self) -> str:
        return "latency" if self.op is not None else "error_rate"

    @property
    def budget(self) -> float:
        """The error budget: the tolerated bad fraction."""
        return 1.0 - self.objective


@dataclass(frozen=True)
class SloStatus:
    """One SLO's judgment at evaluation time."""

    name: str
    kind: str
    objective: float
    compliance: float  # observed good fraction (NaN-free: 1.0 when no traffic)
    burn_rate: float  # bad_fraction / budget; 0 when no traffic
    status: str  # "ok" | "at_risk" | "violated"
    observed: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "kind": self.kind,
            "objective": self.objective,
            "compliance": self.compliance,
            "burn_rate": self.burn_rate,
            "status": self.status,
            "observed": dict(self.observed),
        }


@dataclass(frozen=True)
class HealthReport:
    """Readiness-probe snapshot: overall verdict + per-SLO detail."""

    healthy: bool
    slos: Tuple[SloStatus, ...]
    generated_at: float
    generated_mono: float
    telemetry_enabled: bool

    def status_of(self, name: str) -> Optional[SloStatus]:
        return next((s for s in self.slos if s.name == name), None)

    def to_json(self) -> Dict[str, Any]:
        payload = {
            "healthy": self.healthy,
            "telemetry_enabled": self.telemetry_enabled,
            "generated_at": self.generated_at,
            "generated_mono": self.generated_mono,
            "slos": [s.to_json() for s in self.slos],
        }
        json.dumps(payload)  # serializability guaranteed at the source
        return payload


def _judge(burn: float) -> str:
    # burn <= 1.0 is exactly compliance >= objective (budget consumed no
    # faster than sustainable); FAST_BURN is the page-now line
    if burn <= 1.0:
        return "ok"
    return "at_risk" if burn <= FAST_BURN else "violated"


class SloTracker:  # concurrency: shared probe threads evaluate while ingestion mutates telemetry
    """Evaluate a set of SLOs against the process-wide telemetry registry.

    Error-rate SLOs need *windows*, not lifetime ratios: every
    :meth:`health_report` call checkpoints the summed counter totals and
    computes deltas against the oldest checkpoint still inside each SLO's
    ``window_s`` — so :meth:`health_report` is the probe entry point; the
    lower-level :meth:`evaluate` judges without advancing the window. The
    first report (no prior checkpoint) judges the lifetime totals —
    conservative, and correct for fresh processes.
    """

    def __init__(self, slos: Optional[List[SLO]] = None, registry: Any = None) -> None:
        self.slos: Tuple[SLO, ...] = tuple(slos if slos is not None else DEFAULT_SLOS)
        names = [s.name for s in self.slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {sorted(n for n in names if names.count(n) > 1)}")
        self._registry = registry if registry is not None else REGISTRY
        self._lock = _san_lock("SloTracker._lock")
        max_window = max((s.window_s for s in self.slos), default=300.0)
        self._max_window = max_window
        # (mono, {family: summed total}) checkpoints, oldest first; bounded
        # by time-based pruning + interior thinning in health_report — a
        # deque maxlen would evict the oldest entry under frequent probes
        # and silently shrink the effective error-budget window
        self._checkpoints: "deque[Tuple[float, Dict[str, float]]]" = deque()

    # ------------------------------------------------------------ counter math
    def _family_totals(self) -> Dict[str, float]:
        """Counter totals summed over classes AND labels, keyed by family."""
        totals: Dict[str, float] = {}
        for key, val in self._registry.counter_totals().items():
            family, _labels = _split_key(key)
            totals[family] = totals.get(family, 0.0) + float(val)
        return totals

    def _window_delta(
        self, slo: SLO, now: float, totals: Dict[str, float]
    ) -> Tuple[float, float, float]:
        """(bad_delta, total_delta, window_span_s) for one error-rate SLO.

        The base is the OLDEST checkpoint still inside ``window_s`` (so the
        budget judges the whole window, not just the last probe interval);
        when every checkpoint has aged past the window, the newest one is
        used instead — "since the previous evaluation" beats falling back to
        the lifetime ratio, which would let ancient good traffic mask a
        current burn.
        """
        base: Optional[Dict[str, float]] = None
        base_t = now
        with self._lock:
            if _SAN.enabled:
                _san_check(self, "_checkpoints")
            for t, snap in self._checkpoints:
                if now - t <= slo.window_s:
                    base, base_t = snap, t
                    break
            if base is None and self._checkpoints:
                base_t, base = self._checkpoints[-1]
        bad_now = sum(totals.get(f, 0.0) for f in slo.bad)
        total_now = sum(totals.get(f, 0.0) for f in slo.total)
        if base is None:
            return bad_now, total_now, slo.window_s
        bad_then = sum(base.get(f, 0.0) for f in slo.bad)
        total_then = sum(base.get(f, 0.0) for f in slo.total)
        # counters are monotonic per process; a registry reset mid-window
        # makes deltas negative — clamp rather than report a negative burn
        return max(0.0, bad_now - bad_then), max(0.0, total_now - total_then), max(1e-9, now - base_t)

    def _pooled_latency(self, op: str) -> List[float]:
        values: List[float] = []
        for telem in self._registry.telemetries():
            res = dict(telem.reservoirs).get(op)
            if res is not None:
                values.extend(res.values())
        return values

    # -------------------------------------------------------------- evaluation
    def evaluate(self, slo: SLO, totals: Optional[Dict[str, float]] = None) -> SloStatus:
        """Judge one SLO WITHOUT advancing the error-budget window (only
        :meth:`health_report` checkpoints — wire probes to it, not here).

        ``totals`` lets :meth:`health_report` share ONE registry-aggregate
        walk across every error-rate SLO and the window checkpoint (also
        keeping the judged totals and the checkpointed totals identical — a
        counter advancing mid-report would otherwise be judged in neither
        window or both)."""
        if slo.kind == "latency":
            values = self._pooled_latency(slo.op)
            threshold_s = slo.threshold_ms / 1000.0
            if not values:
                return SloStatus(slo.name, slo.kind, slo.objective, 1.0, 0.0, "ok",
                                 observed={"samples": 0})
            good = sum(1 for v in values if v <= threshold_s)
            compliance = good / len(values)
            burn = (1.0 - compliance) / slo.budget
            svals = sorted(values)
            observed = {
                "samples": len(values),
                "threshold_ms": slo.threshold_ms,
                # nearest_rank is the one quantile formula shared with the
                # Prometheus summary, so probe and scrape agree exactly
                "p50_ms": nearest_rank(svals, 0.50) * 1000.0,
                "p99_ms": nearest_rank(svals, 0.99) * 1000.0,
                "worst_ms": svals[-1] * 1000.0,
            }
            return SloStatus(slo.name, slo.kind, slo.objective, compliance, burn,
                             _judge(burn), observed)
        if totals is None:
            totals = self._family_totals()
        bad, total, span = self._window_delta(slo, time.monotonic(), totals)
        if total <= 0:
            if bad > 0:
                # bad events with zero denominator traffic (e.g. restore
                # fallbacks while ingestion is paused): every observed
                # operation in the window failed — full burn, never "ok"
                burn = 1.0 / slo.budget
                return SloStatus(slo.name, slo.kind, slo.objective, 0.0, burn, _judge(burn),
                                 observed={"bad": bad, "total": 0.0, "window_s": span})
            return SloStatus(slo.name, slo.kind, slo.objective, 1.0, 0.0, "ok",
                             observed={"bad": bad, "total": 0.0, "window_s": span})
        bad_frac = min(1.0, bad / total)
        compliance = 1.0 - bad_frac
        burn = bad_frac / slo.budget
        observed = {"bad": bad, "total": total, "window_s": span,
                    "families": {"bad": list(slo.bad), "total": list(slo.total)}}
        return SloStatus(slo.name, slo.kind, slo.objective, compliance, burn,
                         _judge(burn), observed)

    def health_report(self) -> HealthReport:
        """Evaluate every SLO and checkpoint the counters for the next window."""
        totals = self._family_totals()  # ONE aggregate walk shared by all
        statuses = tuple(self.evaluate(slo, totals) for slo in self.slos)
        now = time.monotonic()
        with self._lock:
            self._checkpoints.append((now, totals))
            # age out checkpoints no SLO's window can reach anymore
            while self._checkpoints and now - self._checkpoints[0][0] > self._max_window * 2:
                self._checkpoints.popleft()
            # memory bound for fast probes: thin every other INTERIOR entry
            # (oldest anchors the window base, newest is the latest delta)
            if len(self._checkpoints) > _MAX_CHECKPOINTS:
                entries = list(self._checkpoints)
                self._checkpoints = deque([entries[0]] + entries[1:-1][::2] + [entries[-1]])
        return HealthReport(
            healthy=all(s.status != "violated" for s in statuses),
            slos=statuses,
            generated_at=time.time(),
            generated_mono=now,
            telemetry_enabled=OBS.enabled,
        )


# Sensible defaults for the serving runtime: ingest latency on the two
# batched hot paths + quarantine/degradation error budgets. Deployments
# replace these with set_slos([...]) sized to their own targets.
DEFAULT_SLOS: List[SLO] = [
    SLO(name="ingest_p99", op="stream_step", threshold_ms=50.0, objective=0.99),
    SLO(name="update_p99", op="update_compiled", threshold_ms=50.0, objective=0.99),
    # the serving runtime's two request-facing ops: enqueue-to-ack for
    # updates, dispatch-to-value for reads (MetricServer observes both)
    SLO(name="serve_ingest_p99", op="ingest", threshold_ms=250.0, objective=0.99),
    SLO(name="serve_compute_p99", op="serve_compute", threshold_ms=250.0, objective=0.99),
    SLO(
        name="quarantine_budget",
        bad=("quarantined_batches",),
        total=("update_calls",),
        objective=0.999,
    ),
    SLO(
        name="degradation_budget",
        bad=("degradations",),
        total=("sync_calls", "update_calls"),
        objective=0.999,
    ),
]


_tracker_lock = _san_lock("slo._tracker_lock")
_tracker: List[SloTracker] = []  # 0 or 1 process-wide tracker (lock-scoped swap)


def set_slos(slos: Optional[List[SLO]] = None) -> SloTracker:
    """Install the process-wide SLO set (None restores the defaults)."""
    tracker = SloTracker(slos)
    with _tracker_lock:
        _tracker[:] = [tracker]
    return tracker


def health_report() -> HealthReport:
    """Readiness snapshot from the process-wide tracker (defaults on first use)."""
    with _tracker_lock:
        tracker = _tracker[0] if _tracker else None
    if tracker is None:
        tracker = set_slos(None)
    return tracker.health_report()
