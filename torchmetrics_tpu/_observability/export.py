"""Export surfaces: Prometheus text exposition + JSON.

:func:`render_prometheus` emits the classic text exposition format
(``text/plain; version=0.0.4``): one ``# HELP``/``# TYPE`` header per metric
family, all samples of a family contiguous, label values escaped per the
spec (backslash, double-quote, newline). The output is validated against
``prometheus_client.parser`` in the test suite.

Counter keys arrive in the registry's flat ``"family|label=value"``
convention and are re-expanded into label sets here; every sample
additionally carries a ``metric="<ClassName>"`` label identifying the
aggregated metric class.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Tuple

from torchmetrics_tpu._observability.telemetry import _split_key

__all__ = ["render_prometheus", "to_json", "EXPORT_VERSION"]

EXPORT_VERSION = 1

_PREFIX = "tmtpu"

# family -> help text; families not listed get a generic line. Counter
# families (monotonic) are exported with the `_total` suffix per convention.
_HELP: Dict[str, str] = {
    "update_calls": "Metric update/forward executions by path taken.",
    "scan_steps": "Individual batches consumed through scan_update streams.",
    "fingerprint": "Host-attribute fingerprint guard outcomes on eager updates.",
    "quarantined_batches": "Batches dropped by the nan_policy='quarantine' sentinel.",
    "deferred_violations": "Compiled validate_args violations surfaced at host sync points.",
    "compute_calls": "compute() invocations by cache outcome.",
    "compiles": "Compiled-executable cache keys built, by compile kind.",
    "recompiles": "Additional cache keys beyond the first per compile kind (churn).",
    "churn_warnings": "Recompile-churn warnings emitted.",
    "churn_suppressed": "Recompile-churn warnings suppressed by rate limiting.",
    "trace_seconds": "Wall-clock seconds spent in first-call trace+lower+execute of compiled paths.",
    "sync_calls": "Distributed state synchronizations started, by guard mode.",
    "sync_attempts": "Guarded-sync collective attempts (includes retries).",
    "sync_retries": "Guarded-sync attempts beyond the first.",
    "degradations": "Recorded degradation events by kind (also on the event bus).",
    "snapshot_writes": "Snapshot generations written by the durability layer.",
    "snapshot_bytes": "Serialized snapshot payload bytes written.",
    "journal_entries": "Update-journal frames appended.",
    "journal_bytes": "Update-journal bytes appended.",
    "restores": "Snapshot restore outcomes.",
    "restore_replayed_updates": "Journaled updates replayed during restores.",
    "events": "Event-bus publishes by kind (lifetime, monotonic).",
    "uncompiled_signatures": "Distinct signatures streamed eagerly past the saturated auto cache.",
    "events_dropped": "Event-bus entries evicted by the capacity bound.",
    "latency_samples": "Lifetime latency samples recorded per op reservoir (monotonic).",
    "latency_sum_seconds": "Lifetime sum of sampled latency seconds per op (monotonic).",
    "latency_seconds": (
        "Sampled operation latency as a Prometheus summary: quantiles over the retained"
        " reservoir window, count/sum lifetime-monotonic."
    ),
    "telemetry_enabled": "1 while the telemetry layer is collecting.",
    "pool_stream_updates": "Per-tenant applied StreamPool rows (bounded stream= label dimension).",
    "pool_quarantined": "Per-tenant StreamPool rows dropped by the NaN quarantine.",
    "pool_violations": "Per-tenant StreamPool rows dropped by error-severity validation flags.",
    "pool_attach": "StreamPool attach() calls.",
    "pool_detach": "StreamPool detach() calls.",
    "pool_growths": "StreamPool capacity-doubling growth events.",
    "pool_computes": "StreamPool compute dispatches by kind (cache misses only).",
    "predicted_state_bytes": (
        "Closed-form predicted metric-state bytes from the static memory cost model"
        " (memory.json), summed over live instances; per-device for SPMD engines."
    ),
    "memory_model_drift": "Memory sanitizer drift findings (predicted vs live bytes).",
}

# reservoir quantiles exported as summary lines (satellite: p50/p90/p99 per op)
_SUMMARY_QUANTILES = (("p50", "0.5"), ("p90", "0.9"), ("p99", "0.99"))

def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(value: float) -> str:
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, int) or (isinstance(value, float) and value.is_integer()):
        return str(int(value))
    return repr(float(value))


def _sample(name: str, labels: Dict[str, str], value: float) -> str:
    if labels:
        inner = ",".join(f'{k}="{_escape_label(str(v))}"' for k, v in sorted(labels.items()))
        return f"{name}{{{inner}}} {_fmt_value(value)}"
    return f"{name} {_fmt_value(value)}"


def render_prometheus(aggregate: Dict[str, Dict[str, Any]], bus: Any, enabled: bool) -> str:
    """Text exposition of the registry aggregate + event-bus counts."""
    # family -> (type, help, [sample lines]) — assembled first so each
    # family renders contiguously regardless of per-class interleaving
    families: Dict[str, Tuple[str, str, List[str]]] = {}

    def emit(family: str, labels: Dict[str, str], value: float, kind: str = "counter") -> None:
        name = f"{_PREFIX}_{family}"
        if kind == "counter":
            name += "_total"
        entry = families.get(name)
        if entry is None:
            help_text = _HELP.get(family, f"torchmetrics_tpu runtime telemetry: {family}.")
            entry = families[name] = (kind, help_text, [])
        entry[2].append(_sample(name, labels, value))

    emit("telemetry_enabled", {}, 1 if enabled else 0, kind="gauge")
    for cls_name in sorted(aggregate):
        entry = aggregate[cls_name]
        base = {"metric": cls_name}
        # ops with any latency evidence: a live retained window, or lifetime
        # counters left behind by retired instances (count/sum still export)
        summary_ops = set(entry["latency"])
        for key in sorted(entry["counters"]):
            family, labels = _split_key(key)
            if family in ("latency_samples", "latency_sum_seconds"):
                # these two ride the latency summary below as `_count`/`_sum`
                # series — re-emitting them as standalone counter families
                # would export every sample twice under two names
                if "op" in labels:
                    summary_ops.add(labels["op"])
                continue
            emit(family, {**base, **labels}, entry["counters"][key])
        for key in sorted(entry.get("gauges", ())):
            family, labels = _split_key(key)
            emit(family, {**base, **labels}, entry["gauges"][key], kind="gauge")
        for op in sorted(summary_ops):
            # Prometheus summary: quantile-labelled samples over the retained
            # reservoir window + lifetime-monotonic `_sum`/`_count` drawn from
            # the regular counters (they survive instance GC; the window
            # doesn't). An op known only from retired counters emits sum/count
            # with no quantiles — a valid, honest summary.
            stats = entry["latency"].get(op, {})
            labels = {**base, "op": op}
            name = f"{_PREFIX}_latency_seconds"
            fam = families.get(name)
            if fam is None:
                fam = families[name] = ("summary", _HELP["latency_seconds"], [])
            for stat, q in _SUMMARY_QUANTILES:
                if stat in stats:
                    fam[2].append(_sample(name, {**labels, "quantile": q}, stats[stat]))
            lifetime_sum = entry["counters"].get(f"latency_sum_seconds|op={op}", stats.get("sum", 0.0))
            lifetime_count = entry["counters"].get(f"latency_samples|op={op}", stats.get("count", 0))
            fam[2].append(_sample(f"{name}_sum", labels, lifetime_sum))
            fam[2].append(_sample(f"{name}_count", labels, lifetime_count))
    for kind_name, count in sorted(bus.kind_totals().items()):
        emit("events", {"kind": kind_name}, count)
    emit("events_dropped", {}, bus.dropped)

    lines: List[str] = []
    for name in sorted(families):
        kind, help_text, samples = families[name]
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        lines.extend(samples)
    return "\n".join(lines) + "\n"


def to_json(aggregate: Dict[str, Dict[str, Any]], bus: Any, enabled: bool) -> Dict[str, Any]:
    """JSON-serializable snapshot (validated round-trippable in tests)."""
    payload = {
        "version": EXPORT_VERSION,
        "enabled": bool(enabled),
        "metrics": {
            name: {
                "counters": {k: v for k, v in sorted(entry["counters"].items())},
                "gauges": {k: v for k, v in sorted(entry.get("gauges", {}).items())},
                "latency": entry["latency"],
                "instances": entry["instances"],
                "retired_instances": entry["retired_instances"],
            }
            for name, entry in sorted(aggregate.items())
        },
        "events": [
            {
                "seq": e.seq,
                "ts": e.ts,
                "mono": e.mono,
                "kind": e.kind,
                "source": e.source,
                "detail": e.detail,
                "data": e.data,
            }
            for e in bus.events()
        ],
        "events_dropped": bus.dropped,
    }
    # guarantee serializability at the source rather than at the caller
    json.dumps(payload)
    return payload
