"""Export surfaces: Prometheus text exposition, OpenMetrics + JSON.

:func:`render_prometheus` emits the classic text exposition format
(``text/plain; version=0.0.4``): one ``# HELP``/``# TYPE`` header per metric
family, all samples of a family contiguous, label values escaped per the
spec (backslash, double-quote, newline). The output is validated against
``prometheus_client.parser`` in the test suite.

:func:`render_openmetrics` emits the same families in OpenMetrics syntax
(``application/openmetrics-text``): counter families are declared WITHOUT
the ``_total`` suffix (samples keep it), latency histogram buckets carry
trace-id **exemplars** (``# {trace_id="..."} value ts``) when tracing was
active at observation time, and the exposition terminates with ``# EOF``.
Classic Prometheus text format has no exemplar syntax — that is the whole
reason this second renderer exists.

Counter keys arrive in the registry's flat ``"family|label=value"``
convention and are re-expanded into label sets here; every sample
additionally carries a ``metric="<ClassName>"`` label identifying the
aggregated metric class.

:data:`EXPORT_SCHEMA` declares every family this module may emit — name,
sample kind, and the complete allowed label set. It is the source of truth
for the checked-in perf manifest (``tools/perf_manifest.py`` /
``_analysis/perf_manifest.json``): adding or relabeling a family without
regenerating the manifest fails tier-1, exactly like the compile golden.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

from torchmetrics_tpu._observability.telemetry import _BUCKET_LABELS, _split_key

__all__ = [
    "render_prometheus",
    "render_openmetrics",
    "to_json",
    "EXPORT_VERSION",
    "EXPORT_SCHEMA",
]

EXPORT_VERSION = 2

_PREFIX = "tmtpu"

# family -> help text; families not listed get a generic line. Counter
# families (monotonic) are exported with the `_total` suffix per convention.
_HELP: Dict[str, str] = {
    "update_calls": "Metric update/forward executions by path taken.",
    "scan_steps": "Individual batches consumed through scan_update streams.",
    "fingerprint": "Host-attribute fingerprint guard outcomes on eager updates.",
    "quarantined_batches": "Batches dropped by the nan_policy='quarantine' sentinel.",
    "deferred_violations": "Compiled validate_args violations surfaced at host sync points.",
    "compute_calls": "compute() invocations by cache outcome.",
    "compiles": "Compiled-executable cache keys built, by compile kind.",
    "recompiles": "Additional cache keys beyond the first per compile kind (churn).",
    "churn_warnings": "Recompile-churn warnings emitted.",
    "churn_suppressed": "Recompile-churn warnings suppressed by rate limiting.",
    "trace_seconds": "Wall-clock seconds spent in first-call trace+lower+execute of compiled paths.",
    "sync_calls": "Distributed state synchronizations started, by guard mode.",
    "sync_attempts": "Guarded-sync collective attempts (includes retries).",
    "sync_retries": "Guarded-sync attempts beyond the first.",
    "degradations": "Recorded degradation events by kind (also on the event bus).",
    "snapshot_writes": "Snapshot generations written by the durability layer.",
    "snapshot_bytes": "Serialized snapshot payload bytes written.",
    "journal_entries": "Update-journal frames appended.",
    "journal_bytes": "Update-journal bytes appended.",
    "restores": "Snapshot restore outcomes.",
    "restore_replayed_updates": "Journaled updates replayed during restores.",
    "events": "Event-bus publishes by kind (lifetime, monotonic).",
    "uncompiled_signatures": "Distinct signatures streamed eagerly past the saturated auto cache.",
    "events_dropped": "Event-bus entries evicted by the capacity bound.",
    "latency_samples": "Lifetime latency samples recorded per op reservoir (monotonic).",
    "latency_sum_seconds": "Lifetime sum of sampled latency seconds per op (monotonic).",
    "latency_seconds": (
        "Sampled operation latency as a Prometheus summary: quantiles over the retained"
        " reservoir window, count/sum lifetime-monotonic."
    ),
    "latency_hist_seconds": (
        "Sampled operation latency as a cumulative histogram (lifetime-monotonic"
        " buckets; carries trace-id exemplars in the OpenMetrics exposition)."
    ),
    "telemetry_enabled": "1 while the telemetry layer is collecting.",
    "profiling_enabled": "1 while the continuous-profiling cost ledger is recording.",
    "pool_stream_updates": "Per-tenant applied StreamPool rows (bounded stream= label dimension).",
    "pool_quarantined": "Per-tenant StreamPool rows dropped by the NaN quarantine.",
    "pool_violations": "Per-tenant StreamPool rows dropped by error-severity validation flags.",
    "pool_attach": "StreamPool attach() calls.",
    "pool_detach": "StreamPool detach() calls.",
    "pool_growths": "StreamPool capacity-doubling growth events.",
    "pool_computes": "StreamPool compute dispatches by kind (cache misses only).",
    "serving_requests": "MetricServer requests by outcome (accepted/rejected/shed/served/failed).",
    "serving_batches": "Micro-batches dispatched by the ingest worker.",
    "serving_batch_rows": "Live rows dispatched across all micro-batches (excludes bucket padding).",
    "serving_controller_decisions": "SLO control-loop decisions by action (grow/shrink/shed/hold).",
    "serving_shed_episodes": "Load-shedding episodes entered at the ingress edge.",
    "serving_recoveries": "Preemption kill/restore cycles completed by the serving runtime.",
    "serving_batch_target": "Current micro-batch size target chosen by the SLO control loop.",
    "serving_ingest_burn": "Latest ingest-latency SLO burn rate seen by the control loop.",
    "serving_queue_depth": "Current bounded ingress-queue depth.",
    "pool_cost_device_seconds": (
        "Per-tenant apportioned micro-batch device seconds (equal share per applied row;"
        " bounded stream= label dimension)."
    ),
    "pool_cost_flops": (
        "Per-tenant apportioned XLA cost_analysis flops for executed stream steps."
    ),
    "pool_cost_state_byte_updates": (
        "Per-tenant predicted state bytes touched (closed-form per-row footprint x"
        " applied row updates)."
    ),
    "predicted_state_bytes": (
        "Closed-form predicted metric-state bytes from the static memory cost model"
        " (memory.json), summed over live instances; per-device for SPMD engines."
    ),
    "memory_model_drift": "Memory sanitizer drift findings (predicted vs live bytes).",
    "profile_device_seconds": "Measured wall seconds of profiled steps per (seam, class).",
    "profile_flops": "XLA cost_analysis flops accrued by profiled steps per (seam, class).",
    "profile_steps": "Profiled step executions per (seam, class).",
    "profile_unattributed_steps": (
        "Profiled steps with no executable cost claim (flops unattributed) per (seam, class)."
    ),
    "profile_mfu": (
        "Cumulative model-flops-utilization per (seam, class): accrued flops /"
        " (device seconds x peak flops)."
    ),
    "profile_roofline_ceiling": (
        "Roofline MFU ceiling per (seam, class) from the executable's arithmetic"
        " intensity and the active bandwidth/peak ceilings."
    ),
    "profile_compile_seconds": "Trace+lower+compile wall seconds per executable digest.",
    "aot_cache": "AOT executable cache load outcomes.",
    "fleet_rollups": (
        "Fleet aggregation-tree rollups completed per region, by outcome"
        " (full/partial; bounded region= label dimension)."
    ),
    "fleet_contributions": "Child contributions folded by fleet rollups per region.",
    "fleet_late_arrivals": (
        "Straggler contributions folded after their epoch's deadline per region."
    ),
    "fleet_duplicates_dropped": (
        "Redelivered/zombie contributions dropped by the epoch fence per region."
    ),
    "fleet_corrupt_quarantined": (
        "Contributions quarantined by integrity verification at fold time per region."
    ),
    "fleet_publish_attempts": "Guarded fleet publish attempts (includes retries) per region.",
    "fleet_rollup_staleness_ms": (
        "Age of the oldest contribution folded by the latest rollup per region."
    ),
}

# Every family the exporters may emit: sample kind + complete allowed label
# set. `metric` is the aggregation class label; histogram/summary synthetic
# labels (`le`, `quantile`) are listed explicitly. tools/perf_manifest.py
# freezes this table into _analysis/perf_manifest.json and tier-1 asserts
# the two stay identical AND that rendered output never strays outside it.
EXPORT_SCHEMA: Dict[str, Dict[str, Any]] = {
    "telemetry_enabled": {"kind": "gauge", "labels": ()},
    "profiling_enabled": {"kind": "gauge", "labels": ()},
    "update_calls": {"kind": "counter", "labels": ("metric", "path")},
    "scan_steps": {"kind": "counter", "labels": ("metric",)},
    "fingerprint": {"kind": "counter", "labels": ("metric", "outcome")},
    "quarantined_batches": {"kind": "counter", "labels": ("metric",)},
    "deferred_violations": {"kind": "counter", "labels": ("metric", "severity")},
    "compute_calls": {"kind": "counter", "labels": ("metric", "outcome")},
    "compiles": {"kind": "counter", "labels": ("metric", "kind")},
    "recompiles": {"kind": "counter", "labels": ("metric", "kind")},
    "uncompiled_signatures": {"kind": "counter", "labels": ("metric", "kind")},
    "churn_warnings": {"kind": "counter", "labels": ("metric",)},
    "churn_suppressed": {"kind": "counter", "labels": ("metric",)},
    "trace_seconds": {"kind": "counter", "labels": ("metric",)},
    "auto_path_disabled": {"kind": "counter", "labels": ("metric",)},
    "signature_overflow": {"kind": "counter", "labels": ("metric",)},
    "sync_calls": {"kind": "counter", "labels": ("metric", "mode")},
    "sync_attempts": {"kind": "counter", "labels": ("metric",)},
    "sync_retries": {"kind": "counter", "labels": ("metric",)},
    "degradations": {"kind": "counter", "labels": ("metric", "kind")},
    "snapshot_writes": {"kind": "counter", "labels": ("metric",)},
    "snapshot_bytes": {"kind": "counter", "labels": ("metric",)},
    "journal_entries": {"kind": "counter", "labels": ("metric",)},
    "journal_bytes": {"kind": "counter", "labels": ("metric",)},
    "restores": {"kind": "counter", "labels": ("metric", "outcome")},
    "restore_replayed_updates": {"kind": "counter", "labels": ("metric",)},
    "aot_cache": {"kind": "counter", "labels": ("metric", "result")},
    "memory_model_drift": {"kind": "counter", "labels": ("metric",)},
    "pool_stream_updates": {"kind": "counter", "labels": ("metric", "stream")},
    "pool_quarantined": {"kind": "counter", "labels": ("metric", "stream")},
    "pool_violations": {"kind": "counter", "labels": ("metric", "stream")},
    "pool_attach": {"kind": "counter", "labels": ("metric",)},
    "pool_detach": {"kind": "counter", "labels": ("metric",)},
    "pool_growths": {"kind": "counter", "labels": ("metric",)},
    "pool_computes": {"kind": "counter", "labels": ("metric", "kind")},
    "pool_cost_device_seconds": {"kind": "counter", "labels": ("metric", "stream")},
    "pool_cost_flops": {"kind": "counter", "labels": ("metric", "stream")},
    "pool_cost_state_byte_updates": {"kind": "counter", "labels": ("metric", "stream")},
    "predicted_state_bytes": {"kind": "gauge", "labels": ("metric", "scope")},
    "events": {"kind": "counter", "labels": ("kind",)},
    "events_dropped": {"kind": "counter", "labels": ()},
    "latency_seconds": {"kind": "summary", "labels": ("metric", "op", "quantile")},
    "latency_hist_seconds": {"kind": "histogram", "labels": ("metric", "op", "le")},
    "profile_device_seconds": {"kind": "counter", "labels": ("seam", "class")},
    "profile_flops": {"kind": "counter", "labels": ("seam", "class")},
    "profile_steps": {"kind": "counter", "labels": ("seam", "class")},
    "profile_unattributed_steps": {"kind": "counter", "labels": ("seam", "class")},
    "profile_mfu": {"kind": "gauge", "labels": ("seam", "class")},
    "profile_roofline_ceiling": {"kind": "gauge", "labels": ("seam", "class")},
    "profile_compile_seconds": {"kind": "counter", "labels": ("digest", "kind", "class")},
    "serving_requests": {"kind": "counter", "labels": ("metric", "outcome")},
    "serving_batches": {"kind": "counter", "labels": ("metric",)},
    "serving_batch_rows": {"kind": "counter", "labels": ("metric",)},
    "serving_controller_decisions": {"kind": "counter", "labels": ("metric", "action")},
    "serving_shed_episodes": {"kind": "counter", "labels": ("metric",)},
    "serving_recoveries": {"kind": "counter", "labels": ("metric",)},
    "serving_batch_target": {"kind": "gauge", "labels": ("metric",)},
    "serving_ingest_burn": {"kind": "gauge", "labels": ("metric",)},
    "serving_queue_depth": {"kind": "gauge", "labels": ("metric",)},
    "fleet_rollups": {"kind": "counter", "labels": ("metric", "region", "outcome")},
    "fleet_contributions": {"kind": "counter", "labels": ("metric", "region")},
    "fleet_late_arrivals": {"kind": "counter", "labels": ("metric", "region")},
    "fleet_duplicates_dropped": {"kind": "counter", "labels": ("metric", "region")},
    "fleet_corrupt_quarantined": {"kind": "counter", "labels": ("metric", "region")},
    "fleet_publish_attempts": {"kind": "counter", "labels": ("metric", "region")},
    "fleet_rollup_staleness_ms": {"kind": "gauge", "labels": ("metric", "region")},
}

# reservoir quantiles exported as summary lines (satellite: p50/p90/p99 per op)
_SUMMARY_QUANTILES = (("p50", "0.5"), ("p90", "0.9"), ("p99", "0.99"))

# counter families that ride a synthetic family (summary/histogram) instead
# of exporting standalone — re-emitting them would double every sample
_SYNTHETIC_SOURCES = frozenset({"latency_samples", "latency_sum_seconds", "latency_bucket"})


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(value: float) -> str:
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, int) or (isinstance(value, float) and value.is_integer()):
        return str(int(value))
    return repr(float(value))


def _sample(name: str, labels: Dict[str, str], value: float) -> str:
    if labels:
        inner = ",".join(f'{k}="{_escape_label(str(v))}"' for k, v in sorted(labels.items()))
        return f"{name}{{{inner}}} {_fmt_value(value)}"
    return f"{name} {_fmt_value(value)}"


# one exposition sample: (name suffix, labels, value, exemplar-or-None);
# exemplars are (observed value, unix ts, trace id) and only the
# OpenMetrics serializer renders them
_Sample = Tuple[str, Dict[str, str], float, Optional[Tuple[float, float, int]]]


def _build_families(
    aggregate: Dict[str, Dict[str, Any]],
    bus: Any,
    enabled: bool,
    ledger: Any = None,
) -> Dict[str, Tuple[str, str, List[_Sample]]]:
    """Renderer-neutral exposition model: family -> (kind, help, samples).

    Family keys are the BASE name (``tmtpu_update_calls``) — suffixes
    (``_total``/``_sum``/``_count``/``_bucket``) live on the samples, so the
    classic and OpenMetrics serializers can each apply their own naming
    convention without re-walking the aggregate.
    """
    families: Dict[str, Tuple[str, str, List[_Sample]]] = {}

    def emit(
        family: str,
        labels: Dict[str, str],
        value: float,
        kind: str = "counter",
        suffix: str = "",
        exemplar: Optional[Tuple[float, float, int]] = None,
    ) -> None:
        name = f"{_PREFIX}_{family}"
        entry = families.get(name)
        if entry is None:
            help_text = _HELP.get(family, f"torchmetrics_tpu runtime telemetry: {family}.")
            entry = families[name] = (kind, help_text, [])
        entry[2].append((suffix, labels, value, exemplar))

    emit("telemetry_enabled", {}, 1 if enabled else 0, kind="gauge")
    for cls_name in sorted(aggregate):
        entry = aggregate[cls_name]
        base = {"metric": cls_name}
        # ops with any latency evidence: a live retained window, or lifetime
        # counters left behind by retired instances (count/sum still export)
        summary_ops = set(entry["latency"])
        hist_ops: Dict[str, Dict[str, float]] = {}
        for key in sorted(entry["counters"]):
            family, labels = _split_key(key)
            if family in _SYNTHETIC_SOURCES:
                # these ride the latency summary/histogram below as
                # `_count`/`_sum`/`_bucket` series — standalone re-emission
                # would export every sample twice under two names
                if "op" in labels:
                    summary_ops.add(labels["op"])
                    if family == "latency_bucket":
                        hist_ops.setdefault(labels["op"], {})[labels["le"]] = entry[
                            "counters"
                        ][key]
                continue
            emit(family, {**base, **labels}, entry["counters"][key])
        for key in sorted(entry.get("gauges", ())):
            family, labels = _split_key(key)
            emit(family, {**base, **labels}, entry["gauges"][key], kind="gauge")
        exemplars = entry.get("exemplars", {})
        for op in sorted(summary_ops):
            # Prometheus summary: quantile-labelled samples over the retained
            # reservoir window + lifetime-monotonic `_sum`/`_count` drawn from
            # the regular counters (they survive instance GC; the window
            # doesn't). An op known only from retired counters emits sum/count
            # with no quantiles — a valid, honest summary.
            stats = entry["latency"].get(op, {})
            labels = {**base, "op": op}
            for stat, q in _SUMMARY_QUANTILES:
                if stat in stats:
                    emit(
                        "latency_seconds",
                        {**labels, "quantile": q},
                        stats[stat],
                        kind="summary",
                    )
            lifetime_sum = entry["counters"].get(f"latency_sum_seconds|op={op}", stats.get("sum", 0.0))
            lifetime_count = entry["counters"].get(f"latency_samples|op={op}", stats.get("count", 0))
            emit("latency_seconds", labels, lifetime_sum, kind="summary", suffix="_sum")
            emit("latency_seconds", labels, lifetime_count, kind="summary", suffix="_count")
            buckets = hist_ops.get(op)
            if buckets:
                # per-bucket counters are recorded non-cumulative; the
                # cumulative sum of monotonic counters is itself monotonic,
                # so the exposed `le` series can never regress between scrapes
                running = 0.0
                for le in _BUCKET_LABELS:
                    running += buckets.get(le, 0.0)
                    emit(
                        "latency_hist_seconds",
                        {**labels, "le": le},
                        running,
                        kind="histogram",
                        suffix="_bucket",
                        exemplar=exemplars.get(f"{op}|{le}"),
                    )
                emit("latency_hist_seconds", labels, lifetime_sum, kind="histogram", suffix="_sum")
                emit("latency_hist_seconds", labels, running, kind="histogram", suffix="_count")
    for kind_name, count in sorted(bus.kind_totals().items()):
        emit("events", {"kind": kind_name}, count)
    emit("events_dropped", {}, bus.dropped)
    if ledger is not None:
        snap = ledger.snapshot()
        emit("profiling_enabled", {}, 1 if snap.get("enabled") else 0, kind="gauge")
        for row in snap.get("seams", ()):
            labels = {"seam": row["seam"], "class": row["class"]}
            emit("profile_device_seconds", labels, row["device_seconds"])
            emit("profile_flops", labels, row["flops"])
            emit("profile_steps", labels, row["steps"])
            emit("profile_unattributed_steps", labels, row["unattributed_steps"])
            if row.get("mfu") is not None:
                emit("profile_mfu", labels, row["mfu"], kind="gauge")
            if row.get("roofline_ceiling") is not None:
                emit("profile_roofline_ceiling", labels, row["roofline_ceiling"], kind="gauge")
        for digest, rec in sorted(snap.get("executables", {}).items()):
            emit(
                "profile_compile_seconds",
                {"digest": digest, "kind": rec["kind"], "class": rec["class"]},
                rec["compile_seconds"],
            )
    return families


def render_prometheus(
    aggregate: Dict[str, Dict[str, Any]], bus: Any, enabled: bool, ledger: Any = None
) -> str:
    """Classic text exposition of the registry aggregate + event-bus counts."""
    families = _build_families(aggregate, bus, enabled, ledger)
    # classic convention: counter FAMILY names carry `_total`; exemplars are
    # not representable in this format and are dropped
    lines: List[str] = []
    renamed: Dict[str, Tuple[str, str, List[_Sample]]] = {}
    for name, (kind, help_text, samples) in families.items():
        renamed[f"{name}_total" if kind == "counter" else name] = (kind, help_text, samples)
    for name in sorted(renamed):
        kind, help_text, samples = renamed[name]
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        for suffix, labels, value, _exemplar in samples:
            lines.append(_sample(f"{name}{suffix}" if kind != "counter" else name, labels, value))
    return "\n".join(lines) + "\n"


def render_openmetrics(
    aggregate: Dict[str, Dict[str, Any]], bus: Any, enabled: bool, ledger: Any = None
) -> str:
    """OpenMetrics exposition: counter samples get `_total`, histogram
    buckets carry trace-id exemplars, and the stream ends with `# EOF`."""
    families = _build_families(aggregate, bus, enabled, ledger)
    lines: List[str] = []
    for name in sorted(families):
        kind, help_text, samples = families[name]
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        for suffix, labels, value, exemplar in samples:
            sample_suffix = "_total" if kind == "counter" else suffix
            line = _sample(f"{name}{sample_suffix}", labels, value)
            if exemplar is not None and suffix == "_bucket":
                obs_value, obs_ts, trace_id = exemplar
                line += (
                    f' # {{trace_id="{trace_id}"}}'
                    f" {_fmt_value(obs_value)} {_fmt_value(round(obs_ts, 3))}"
                )
            lines.append(line)
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def to_json(
    aggregate: Dict[str, Dict[str, Any]], bus: Any, enabled: bool, ledger: Any = None
) -> Dict[str, Any]:
    """JSON-serializable snapshot (validated round-trippable in tests)."""
    payload = {
        "version": EXPORT_VERSION,
        "enabled": bool(enabled),
        "metrics": {
            name: {
                "counters": {k: v for k, v in sorted(entry["counters"].items())},
                "gauges": {k: v for k, v in sorted(entry.get("gauges", {}).items())},
                "latency": entry["latency"],
                "exemplars": {
                    k: {"value": ex[0], "ts": ex[1], "trace_id": ex[2]}
                    for k, ex in sorted(entry.get("exemplars", {}).items())
                },
                "instances": entry["instances"],
                "retired_instances": entry["retired_instances"],
            }
            for name, entry in sorted(aggregate.items())
        },
        "events": [
            {
                "seq": e.seq,
                "ts": e.ts,
                "mono": e.mono,
                "kind": e.kind,
                "source": e.source,
                "detail": e.detail,
                "data": e.data,
            }
            for e in bus.events()
        ],
        "events_dropped": bus.dropped,
    }
    if ledger is not None:
        payload["profiling"] = ledger.snapshot()
    # guarantee serializability at the source rather than at the caller
    json.dumps(payload)
    return payload
