"""PartitionSpec plumbing for sharded metric-state pytrees.

The SPMD engine (``engine.py``) stores every metric state *stacked*: a state
whose per-device value has shape ``(*s,)`` lives as one global ``(D, *s)``
array sharded ``PartitionSpec(axis_name)`` over a named 1-D mesh — each
device owns exactly its row, which is its local accumulator. Ring-buffer
("cat") states stack the same way as a ``{"data", "valid", "count"}`` leaf
dict. This module derives those specs, the per-state collective plan the
fused step's in-graph sync uses, and validates that a live metric's declared
reductions map onto in-graph collectives at all.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError
from torchmetrics_tpu.utilities.ringbuffer import RingBuffer

__all__ = [
    "COLLECTIVE_FOR",
    "InGraphSyncUnsupported",
    "build_mesh",
    "state_specs",
    "state_sharding",
    "stack_default",
    "sync_plan",
    "validate_reductions",
]


class InGraphSyncUnsupported(TorchMetricsUserError):
    """The metric cannot take the fused in-graph sync path.

    Raised at engine construction — never mid-stream — so callers keep the
    eager gather path (``Metric.sync``) with zero state committed.
    """


# reduction kind -> the XLA collective the fused step lowers it to; the
# actual lowering lives in ``utilities.distributed.sync_in_jit`` — this map
# is the declarative contract tests assert against. ``None`` is the
# reference's "gather, don't reduce" kind (PearsonCorrCoef's algorithmic
# merge): fixed-shape array states all_gather into a stacked ``(D, *s)``
# moment set that the class's own compute folds (``_final_aggregation``).
COLLECTIVE_FOR: Dict[Optional[str], str] = {
    "sum": "psum",
    "mean": "pmean",
    "max": "pmax",
    "min": "pmin",
    "cat": "all_gather",
    None: "all_gather",
}


def build_mesh(axis_name: str = "dp", devices: Optional[Sequence[Any]] = None) -> Mesh:
    """A 1-D named mesh over ``devices`` (default: every local device)."""
    devs = list(devices) if devices is not None else list(jax.devices())
    if not devs:
        raise InGraphSyncUnsupported("no devices available to build a mesh over")
    return Mesh(np.array(devs), (axis_name,))


def state_specs(names: Sequence[str], axis_name: str) -> Dict[str, PartitionSpec]:
    """Stacked-layout specs: the leading device axis shards over ``axis_name``.

    A :class:`PartitionSpec` is a valid tree *prefix*, so the same spec
    covers a plain stacked array and a ring state's ``{data, valid, count}``
    leaf dict (every leaf carries the stacked device axis first).
    """
    return {name: PartitionSpec(axis_name) for name in names}


def state_sharding(mesh: Mesh, axis_name: str) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(axis_name))


def stack_default(default: Any, world: int) -> np.ndarray:
    """Host ``(world, *shape)`` stack of one per-device default value."""
    base = np.asarray(default)
    return np.broadcast_to(base[None], (world, *base.shape)).copy()


def sync_plan(reductions: Dict[str, Any]) -> Dict[str, str]:
    """``state -> collective`` plan for a metric's declared reductions.

    Raises :class:`InGraphSyncUnsupported` (listing every offending state)
    when any reduction has no in-graph collective. This is the runtime twin
    of the manifest's ``in_graph_sync`` facet: the facet proves it statically
    where it can; this check decides the ``"runtime"``-facet classes from the
    live instance.
    """
    plan: Dict[str, str] = {}
    bad: List[str] = []
    for name, red in reductions.items():
        if red is None or (isinstance(red, str) and red in COLLECTIVE_FOR):
            plan[name] = COLLECTIVE_FOR[red]
        else:
            desc = red if isinstance(red, str) else f"callable:{getattr(red, '__name__', 'fn')}"
            bad.append(f"`{name}` (dist_reduce_fx={desc!r})")
    if bad:
        raise InGraphSyncUnsupported(
            "These states declare reductions with no in-graph collective semantics: "
            + ", ".join(sorted(bad))
            + ". The fused SPMD step supports sum/mean/max/min (psum/pmean/pmax/pmin),"
            " ring-buffer cat states and fixed-shape gather (None) states (all_gather);"
            " keep the eager gather path for the rest."
        )
    return plan


def validate_reductions(metric: Any) -> Dict[str, str]:
    """Validate a live metric's states for the fused step; return the plan.

    Beyond reduction kinds, array states with ``dist_reduce_fx="cat"`` are
    rejected unless they are ring buffers: a growing concatenated carry
    changes shape every step, which would retrace the step per batch —
    exactly the pathology ``cat_state_capacity`` exists to bound.
    """
    plan = sync_plan(dict(metric._reductions))
    for name, red in metric._reductions.items():
        value = getattr(metric, name)
        if red == "cat" and not isinstance(value, RingBuffer):
            raise InGraphSyncUnsupported(
                f"state `{name}` is an unbounded cat state; its carried shape would grow"
                " every fused step (one recompile per batch). Construct the metric with"
                " `cat_state_capacity=N` to bound it into a ring buffer."
            )
        if red is None and isinstance(value, list):
            raise InGraphSyncUnsupported(
                f"state `{name}` is a list state with dist_reduce_fx=None; in-graph gather"
                " needs a fixed per-device shape (an array state, as the Pearson moment"
                " states are). Keep the eager gather path."
            )
    return plan
