"""Fault injection for the compiled SPMD step (test/chaos harness).

In-graph collectives cannot be reached through the eager transport seam
(``utilities.distributed._transport``) — they are burned into the XLA
executable. The dispatch seam here is the compiled-path analogue: every
fused-step execution flows through :func:`dispatch`, so tests can make the
*step itself* fail the way a dying ICI fabric or evicted backend does
(``XlaRuntimeError`` out of a dispatched executable) and assert the engine's
degradation contract without needing real hardware faults.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Iterator, Optional

__all__ = ["dispatch", "inject_step_failure"]

# None = healthy; otherwise a zero-arg callable invoked before every
# dispatch — it raises to simulate the failure
_failure: Optional[Callable[[], None]] = None


def dispatch(fn: Callable, *args: Any) -> Any:
    """Execute one compiled step through the patchable seam."""
    if _failure is not None:
        _failure()
    return fn(*args)


@contextlib.contextmanager
def inject_step_failure(
    exc_factory: Optional[Callable[[], BaseException]] = None,
    times: Optional[int] = None,
) -> Iterator[None]:
    """Make fused-step dispatches raise while the context is active.

    ``times`` bounds how many dispatches fail (None = all of them); the
    default exception models an XLA runtime fault (a ``RuntimeError``, which
    the engine treats as degradable — programming errors are not).
    """
    make = exc_factory or (lambda: RuntimeError("injected in-graph collective failure"))
    remaining = [times]

    def fail() -> None:
        if remaining[0] is not None:
            if remaining[0] <= 0:
                return
            remaining[0] -= 1
        raise make()

    global _failure
    prev = _failure
    _failure = fail
    try:
        yield
    finally:
        _failure = prev
