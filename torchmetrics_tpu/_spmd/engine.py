"""SPMD in-graph metric engine: one donated compiled update→sync→compute step.

The eager runtime streams ``update()`` per process and bolts distributed
sync on *after* accumulation — an eager multi-host gather guarded by
``_resilience``. This engine is the TPU-native inversion for data-parallel
streaming over a named device mesh:

- **Sharded state pytrees.** Every registered state lives stacked: a
  per-device value of shape ``(*s,)`` becomes one global ``(D, *s)`` array
  sharded ``PartitionSpec(axis)`` (``specs.py``), so each device owns its
  local accumulator row. Ring-buffer cat states stack their
  ``data/valid/count`` leaves the same way.
- **One donated compiled step.** ``step(batch)`` lowers update (on the
  device's batch shard), cross-device sync (``sync_in_jit``: the declared
  ``dist_reduce_fx`` of each state picked as an in-graph
  ``psum``/``pmean``/``pmax``/``pmin``/``all_gather``), and ``compute`` into
  a single ``jax.jit(shard_map(...), donate_argnums=(0,))`` executable. The
  state buffers are donated — XLA updates them in place, and steady-state
  streaming performs zero per-step host round-trips. The *carried* state
  stays local (unsynced); the sync feeds only the returned value, so
  accumulation semantics match the reference's sync/unsync dance.
- **Eligibility-gated.** The compile-eligibility manifest's
  ``in_graph_sync`` facet gates which classes may take this path
  (host-bound classes keep the eager gather); ``"runtime"``-facet classes
  are re-checked against the live instance's ``_reductions``.
- **Resilience-wrapped.** The structure digest is checked once at trace
  time (multi-host: through the guarded handshake), and any degradable
  failure of the compiled step — an injected or real collective fault —
  folds the device states back into the host metric and falls back to the
  current eager guarded-sync path, recording a ``DegradationEvent``.
- **Observable & durable.** ``update_calls|path=spmd`` counters and sampled
  ``spmd_step`` latency reservoirs flow into the existing telemetry
  registry; a :class:`~torchmetrics_tpu._resilience.snapshot.SnapshotManager`
  attached to the engine snapshots the donated states via host-side
  ``device_get`` at snapshot boundaries (``note_update``).

``MetricCollection`` support fuses *compute groups* into the same single
step: group heads update+sync once, members compute from the head's synced
states in-graph.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from torchmetrics_tpu._analysis.manifest import in_graph_sync_eligible, predicted_state_bytes
from torchmetrics_tpu._aot.state import AOT as _AOT
from torchmetrics_tpu._observability import tracing as _obs_trace
from torchmetrics_tpu._observability.profiling import LEDGER as _PROF_LEDGER
from torchmetrics_tpu._observability.state import OBS as _OBS
from torchmetrics_tpu._observability.telemetry import telemetry_for as _telemetry_for
from torchmetrics_tpu._spmd import faultinject as _faultinject
from torchmetrics_tpu._spmd.specs import (
    InGraphSyncUnsupported,
    build_mesh,
    stack_default,
    state_sharding,
    state_specs,
    validate_reductions,
)
from torchmetrics_tpu.utilities.distributed import shard_map, sync_in_jit
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError
from torchmetrics_tpu.utilities.ringbuffer import RingBuffer

__all__ = ["SpmdEngine"]

# deterministic programming errors re-raise instead of degrading (degrading
# would reduce a bug to a warning with silently-diverged results — the same
# philosophy as the guard's _NON_RETRYABLE set, minus ValueError, which jax
# trace machinery also uses for transient shape/sharding complaints)
_FATAL = (TorchMetricsUserError, TypeError, AttributeError, NameError, KeyError, IndexError)


def _is_array(x: Any) -> bool:
    return isinstance(x, (jnp.ndarray, np.ndarray)) or (hasattr(x, "dtype") and hasattr(x, "shape"))


@dataclass
class _Unit:
    """One fused-step participant: a metric (or compute-group head + members)."""

    key: str  # "" for a bare metric; the head's collection key otherwise
    metric: Any  # the head — its update runs, its states carry
    members: List[Tuple[str, Any]] = field(default_factory=list)  # (name, metric) incl. head
    names: List[str] = field(default_factory=list)
    rings: Dict[str, int] = field(default_factory=dict)  # ring states -> capacity


class SpmdEngine:
    """Drive a Metric or MetricCollection as sharded state + one fused step.

    The target must be fresh (``update_count == 0``): the engine owns the
    stream from the first batch. ``step(*batch)`` consumes a *global* batch
    whose array leaves carry a leading axis divisible by the mesh size, and
    returns the globally-synced metric value for the stream so far.
    """

    def __init__(
        self,
        target: Any,
        *,
        mesh: Any = None,
        axis_name: str = "dp",
        donate: bool = True,
        enforce_manifest: bool = True,
        groups: Optional[Any] = None,
    ) -> None:
        from torchmetrics_tpu.collections import MetricCollection
        from torchmetrics_tpu.metric import Metric

        self._collection = target if isinstance(target, MetricCollection) else None
        if self._collection is None and not isinstance(target, Metric):
            raise InGraphSyncUnsupported(
                f"SpmdEngine target must be a Metric or MetricCollection, got {type(target).__name__}"
            )
        self.target = target
        self.axis_name = axis_name
        self.mesh = mesh if mesh is not None else build_mesh(axis_name)
        if self.axis_name not in self.mesh.axis_names:
            raise InGraphSyncUnsupported(
                f"axis {axis_name!r} not in mesh axes {self.mesh.axis_names}"
            )
        if len(self.mesh.axis_names) != 1:
            raise InGraphSyncUnsupported(
                "SpmdEngine shards states over a 1-D data-parallel mesh; build sub-meshes for"
                " multi-axis layouts (tp/pp state sharding composes at the model level)"
            )
        self.donate = donate
        self.world = int(self.mesh.shape[self.axis_name])
        # axis_index_groups: the in-jit process_group analogue — disjoint
        # equal-sized subgroups of the mesh axis sync independently, keeping
        # e.g. two data-parallel replicas inside ONE fused step. step() then
        # returns a {group_index: value} dict (one synced value per replica).
        self.groups: Optional[Tuple[Tuple[int, ...], ...]] = None
        if groups is not None:
            from torchmetrics_tpu.utilities.distributed import validate_axis_groups

            parsed = tuple(tuple(int(i) for i in g) for g in groups)
            try:
                # one shared invariant with the in-jit grouped selector —
                # surfaced eagerly here, at construction, as the engine's
                # gating error type
                validate_axis_groups(parsed, self.world)
            except ValueError as err:
                raise InGraphSyncUnsupported(
                    f"`groups` must be equal-sized disjoint subgroups partitioning the"
                    f" {self.world}-device `{axis_name}` axis: {err}"
                ) from None
            self.groups = parsed
            self._home_group = next(g for g in parsed if 0 in g)
        self._sharding = state_sharding(self.mesh, self.axis_name)
        metrics = list(target._modules.values()) if self._collection is not None else [target]
        for m in metrics:
            facet = in_graph_sync_eligible(type(m))
            if facet in ("host_bound", "unsupported") and enforce_manifest:
                raise InGraphSyncUnsupported(
                    f"{type(m).__name__} is certified `{facet}` by the eligibility manifest's"
                    " in_graph_sync facet: it keeps the eager gather path"
                    " (`Metric.sync`). Pass enforce_manifest=False only if you know the"
                    " class traces and its reductions map onto in-graph collectives."
                )
            if facet == "unknown" and enforce_manifest:
                raise InGraphSyncUnsupported(
                    f"{type(m).__name__} is absent from the eligibility manifest (user"
                    " subclass?); the in-graph path is certified per-class. Pass"
                    " enforce_manifest=False to opt in without certification."
                )
            # the "runtime" facet (and defense-in-depth for "safe"): the live
            # instance's declared reductions must map onto in-graph collectives
            validate_reductions(m)
            if m._update_count != 0:
                raise InGraphSyncUnsupported(
                    f"{type(m).__name__} has already accumulated {m._update_count} update(s);"
                    " attach the SPMD engine to a fresh metric (the engine owns the stream)"
                )
        # lazy build state (first step learns ring shapes + compute groups)
        self._units: Optional[List[_Unit]] = None
        self._states: Optional[Dict[str, Dict[str, Any]]] = None
        self._stacked_defaults: Optional[Dict[str, Dict[str, Any]]] = None
        self._steps = 0
        self._degraded = False
        self._step_fns: Dict[Any, Any] = {}
        self._compute_fn: Optional[Any] = None
        # SnapshotManager target surface (populated at prepare)
        self._defaults: Dict[str, Any] = {}
        self._snapshot_hook: Optional[Any] = None

    # ------------------------------------------------------------- properties
    @property
    def degraded(self) -> bool:
        """True once the engine fell back to the eager guarded-sync path."""
        return self._degraded

    @property
    def steps(self) -> int:
        return self._steps

    @property
    def _update_count(self) -> int:  # SnapshotManager count-capture surface
        return self._steps

    @_update_count.setter
    def _update_count(self, value: int) -> None:
        self._steps = int(value)

    # ------------------------------------------------------------------ step
    def step(self, *args: Any, **kwargs: Any) -> Any:
        """One fused update+sync+compute over the sharded batch.

        Returns the globally-synced value (a dict keyed like
        ``MetricCollection.compute()`` for collections). In degraded mode
        this is ``target.update(batch); target.compute()`` — the eager
        guarded-sync path the engine replaced.
        """
        _sp = None
        if _OBS.tracing:
            # ONE span for the fused update+sync+compute dispatch; a degraded
            # step's eager fallback opens the ordinary seam spans as children
            _sp = _obs_trace.begin_span(
                "spmd.step", type(self.target).__name__, degraded=self._degraded
            )
        _sp_err: Optional[BaseException] = None
        try:
            return self._step_impl(args, kwargs)
        except BaseException as err:
            _sp_err = err
            raise
        finally:
            if _sp is not None:
                _obs_trace.end_span(_sp, _sp_err)

    def _step_impl(self, args: tuple, kwargs: Dict[str, Any]) -> Any:
        if self._degraded:
            return self._eager_step(args, kwargs)
        if self._units is None:
            self._prepare(args, kwargs)
            if self._degraded:  # trace-time handshake degraded the transport
                return self._eager_step(args, kwargs)
        from torchmetrics_tpu.metric import Metric

        treedef, dynamic, statics = Metric._split_batch_args("spmd_step", args, kwargs)
        if not dynamic:
            raise TorchMetricsUserError("`step` needs at least one array argument to shard")
        for leaf in dynamic:
            if getattr(leaf, "ndim", 0) < 1 or leaf.shape[0] % self.world:
                raise TorchMetricsUserError(
                    f"every array argument must carry a leading batch axis divisible by the"
                    f" mesh size ({self.world}); got shape {getattr(leaf, 'shape', ())}"
                )
        sig = (treedef, statics, tuple((tuple(d.shape), str(d.dtype)) for d in dynamic))
        key = (sig, tuple(
            None if u.metric._dtype_policy is None else jnp.dtype(u.metric._dtype_policy).name
            for u in self._units
        ))
        fn = self._step_fns.get(key)
        built = fn is None
        if built:
            fn = self._build_step(treedef, statics, len(dynamic))
            if _AOT.active or _OBS.profiling:
                fn = self._aot_wrap(fn, "spmd_step", key)
            if _OBS.enabled:
                # first call = trace+lower+execute: time it once, then the
                # shim self-replaces under this cache key (same contract as
                # Metric._compiled_update)
                fn = self._units[0].metric._obs_timed_first_call(self._step_fns, key, fn)
            self._step_fns[key] = fn
        obs_sample = False
        # first (built) calls pay trace+lower+execute — the ledger accounts
        # compile time separately, so they stay out of device-time buckets
        prof = _OBS.profiling and not built
        t0 = 0.0
        if _OBS.enabled:
            telem = _telemetry_for(self.target)
            if built:
                self._units[0].metric._obs_compile_event("spmd_step", treedef, statics, sig[2])
            obs_sample = telem.sample_due("spmd_step")
        if obs_sample or prof:
            t0 = time.perf_counter()
        try:
            new_states, value = _faultinject.dispatch(fn, self._states, dynamic)
        except jax.errors.JAXTypeError as err:
            # trace-time concretization/tracer-leak failures (a compute body
            # the facet could only certify "runtime") are not programming
            # errors in the CALLER: fall back to the eager path the class
            # would have kept without the engine
            self._degrade(f"fused step does not trace: {type(err).__name__}: {err}")
            return self._eager_step(args, kwargs)
        except _FATAL:
            raise
        except Exception as err:  # noqa: BLE001 - collective/backend faults degrade
            self._degrade(f"fused step failed: {type(err).__name__}: {err}")
            return self._eager_step(args, kwargs)
        self._states = new_states
        self._steps += 1
        if obs_sample or prof:
            elapsed = time.perf_counter() - t0
            if prof:
                _PROF_LEDGER.record_step("spmd_step", type(self.target).__name__, elapsed)
        if _OBS.enabled:
            telem = _telemetry_for(self.target)
            telem.inc("update_calls|path=spmd")
            if obs_sample:
                telem.observe("spmd_step", elapsed)
        hook = self.__dict__.get("_snapshot_hook")
        if hook is not None:
            hook.note_update()
        return self._shape_value(value)

    def compute(self) -> Any:
        """Sync+compute on the current sharded states (no update, no donation)."""
        if self._degraded or self._units is None:
            return self.target.compute()
        # the executable bakes in each unit's dtype policy (states cast inside
        # _traced_update/_traced_compute), so a set_dtype between calls must
        # rebuild — same cache-key component the step fns carry
        policies = tuple(
            None if u.metric._dtype_policy is None else jnp.dtype(u.metric._dtype_policy).name
            for u in self._units
        )
        if self._compute_fn is None or self._compute_fn[0] != policies:
            fn = self._build_compute()
            if _AOT.active:
                fn = self._aot_wrap(fn, "spmd_compute", policies)
            self._compute_fn = (policies, fn)
        try:
            value = _faultinject.dispatch(self._compute_fn[1], self._states)
        except jax.errors.JAXTypeError as err:
            # first-ever trace of the compute body can happen HERE (restore
            # before any step): a host-syncing compute is the class's problem,
            # not the caller's — degrade exactly as step() does
            self._degrade(f"fused compute does not trace: {type(err).__name__}: {err}")
            return self.target.compute()
        except _FATAL:
            raise
        except Exception as err:  # noqa: BLE001
            self._degrade(f"fused compute failed: {type(err).__name__}: {err}")
            return self.target.compute()
        return self._shape_value(value)

    def _aot_wrap(self, fn: Any, kind: str, key: Any) -> Any:
        """Route a fresh fused executable through the AOT dispatcher."""
        from torchmetrics_tpu._aot.cache import wrap_executable

        return wrap_executable(
            fn,
            owner=f"SpmdEngine[{type(self.target).__name__}]",
            kind=kind,
            key_repr=repr((key, self.world, self.axis_name)),
            telem_obj=self.target,
        )

    def warm_start(self, *args: Any, **kwargs: Any) -> Dict[str, str]:
        """Pre-resolve the fused step + compute executables for this
        example-batch signature WITHOUT consuming a batch.

        With an AOT cache directory set (``TM_TPU_AOT_CACHE`` /
        ``set_aot_cache``) serialized executables load from disk — no trace,
        no XLA compile; otherwise they are lowered+compiled in memory. The
        example batch must be shaped exactly like real traffic (leading axis
        divisible by the mesh size); the donated state buffers are only
        lowered against, never consumed, and the stream's step count does
        not advance.

        Returns per-executable outcomes: ``"hit"`` (loaded from the cache),
        ``"compiled"``, ``"fallback"``, or ``"ready"`` (already resolved).
        """
        from torchmetrics_tpu.metric import Metric

        if self._degraded:
            return {"spmd_step": "degraded", "spmd_compute": "degraded"}
        if self._units is None:
            self._prepare(args, kwargs)
            if self._degraded:
                return {"spmd_step": "degraded", "spmd_compute": "degraded"}
        treedef, dynamic, statics = Metric._split_batch_args("spmd_step", args, kwargs)
        if not dynamic:
            raise TorchMetricsUserError("`warm_start` needs at least one array argument to shard")
        for leaf in dynamic:
            if getattr(leaf, "ndim", 0) < 1 or leaf.shape[0] % self.world:
                raise TorchMetricsUserError(
                    f"every array argument must carry a leading batch axis divisible by the"
                    f" mesh size ({self.world}); got shape {getattr(leaf, 'shape', ())}"
                )
        sig = (treedef, statics, tuple((tuple(d.shape), str(d.dtype)) for d in dynamic))
        key = (sig, tuple(
            None if u.metric._dtype_policy is None else jnp.dtype(u.metric._dtype_policy).name
            for u in self._units
        ))
        outcomes: Dict[str, str] = {}
        fn = self._step_fns.get(key)
        if fn is None:
            fn = self._aot_wrap(self._build_step(treedef, statics, len(dynamic)), "spmd_step", key)
            # setdefault: concurrent warm_start calls race benignly — both
            # dispatchers are equivalent, the first insert wins for everyone
            fn = self._step_fns.setdefault(key, fn)
            if _OBS.enabled:
                self._units[0].metric._obs_compile_event("spmd_step", treedef, statics, sig[2])
        outcomes["spmd_step"] = fn.warm(self._states, dynamic) if hasattr(fn, "warm") else "ready"
        policies = key[1]
        if self._compute_fn is None or self._compute_fn[0] != policies:
            self._compute_fn = (policies, self._aot_wrap(self._build_compute(), "spmd_compute", policies))
        cfn = self._compute_fn[1]
        outcomes["spmd_compute"] = cfn.warm(self._states) if hasattr(cfn, "warm") else "ready"
        return outcomes

    def _shape_value(self, value: Any) -> Any:
        """Host-facing result: flatten collection dicts; slice replica groups.

        With ``groups`` the fused step returns the per-device value stack
        (each device carries its own group's synced value), so the result is
        ``{group_index: value}`` — one lazily-sliced device array per replica
        group, no forced host sync.
        """
        if self.groups is None:
            if self._collection is not None:
                return self._collection._flatten_results(value)
            return value
        out: Dict[int, Any] = {}
        for gi, g in enumerate(self.groups):
            v = jax.tree_util.tree_map(lambda x, _lead=g[0]: x[_lead], value)
            out[gi] = self._collection._flatten_results(v) if self._collection is not None else v
        return out

    def reset(self) -> None:
        """Reset sharded states (and the host target) to defaults."""
        self._steps = 0
        if self._units is not None and self._stacked_defaults is not None:
            self._states = jax.tree_util.tree_map(
                lambda d: jax.device_put(d, self._sharding), self._stacked_defaults
            )
        self.target.reset()

    # ------------------------------------------------------------ degradation
    def _degrade(self, detail: str) -> None:
        """Fold device states into the host target; future steps go eager.

        The fold merges each state's per-device rows with its own declared
        reduction — exactly what a successful sync would have produced — so
        the eager stream resumes without losing a batch. One fault class
        cannot fold: an EXECUTE-time failure of the donated step has already
        consumed the input buffers (donation deletes them whether or not the
        executable completed), so there is nothing left to read back. The
        stream then restarts from defaults, says so in the degradation
        event, and points at the SnapshotManager — whose boundary
        ``device_get`` snapshots exist precisely to bound this loss.
        """
        folded = False
        if self._units is not None and self._states is not None:
            leaves = jax.tree_util.tree_leaves(self._states)
            consumed = any(
                leaf.is_deleted() for leaf in leaves if hasattr(leaf, "is_deleted")
            )
            if consumed:
                detail += (
                    f"; the failed step had already consumed the donated state buffers —"
                    f" {self._steps} fused step(s) of accumulation are lost and the eager"
                    " stream restarts from defaults (an attached SnapshotManager bounds"
                    " this: restore_latest() returns to the newest snapshot boundary)"
                )
                self._steps = 0
            else:
                try:
                    for unit in self._units:
                        self._fold_unit_to_host(unit)
                    if self._collection is not None:
                        self._collection._sync_compute_groups()
                    folded = True
                    if self.groups is not None:
                        detail += (
                            f"; axis_index_groups were active — the host target can carry"
                            f" only one stream, so the fold merged the home replica group"
                            f" (devices {list(self._home_group)}) and the other groups'"
                            " accumulation stays on their processes"
                        )
                except Exception as fold_err:  # noqa: BLE001 - degrade must never crash
                    detail += (
                        f"; folding device states back failed too"
                        f" ({type(fold_err).__name__}: {fold_err}) — the eager stream"
                        " restarts from defaults"
                    )
                    self._steps = 0
        hook = self.__dict__.get("_snapshot_hook")
        if hook is not None:
            # the manager snapshots THROUGH the engine's state_dict, which
            # needs live device states: capture one final boundary while they
            # exist, then pause — the eager continuation is outside the
            # engine-targeted manager's reach, and that must be said, not
            # discovered at restore time
            if folded:
                try:
                    hook.snapshot_now(_inline=True)
                except Exception:  # noqa: BLE001 - durability must not break the degrade
                    pass
            hook.pause()
            detail += (
                "; the attached SnapshotManager captured a final boundary snapshot and"
                " was PAUSED (it snapshots the fused device states, which no longer"
                " exist) — attach a manager to the target metric for eager-path"
                " durability"
                if folded
                else "; the attached SnapshotManager was PAUSED (no device states left"
                " to snapshot) — attach a manager to the target metric for eager-path"
                " durability"
            )
        self._degraded = True
        self._states = None
        self._step_fns.clear()
        self._compute_fn = None
        primary = self._units[0].metric if self._units else (
            next(iter(self.target._modules.values())) if self._collection is not None else self.target
        )
        primary._record_degradation("spmd_degraded", detail=f"{detail}; falling back to the eager guarded sync path")

    def _eager_step(self, args: tuple, kwargs: Dict[str, Any]) -> Any:
        self.target.update(*args, **kwargs)
        self._steps += 1
        return self.target.compute()

    def _fold_unit_to_host(self, unit: _Unit) -> None:
        m = unit.metric
        states = self._states[unit.key]
        # under axis_index_groups each group is an independent replica; the
        # host target can carry only one stream, so the fold merges the HOME
        # group (the one containing device 0) and says so in the event detail
        devs = list(self._home_group) if self.groups is not None else list(range(self.world))
        gathered: Dict[str, Any] = {}  # dist_reduce_fx=None states fold together
        for n in unit.names:
            red = m._reductions[n]
            if n in unit.rings:
                st = jax.device_get(states[n])
                # group-capacity buffer, matching what sync_in_jit's
                # all_gather produces — folding len(devs)*cap rows into a
                # cap-sized ring would silently drop all but 1/world of them
                rb = RingBuffer(unit.rings[n] * len(devs))
                for d in devs:
                    rows = np.asarray(st["data"][d])[np.asarray(st["valid"][d])]
                    if rows.shape[0]:
                        rb.append(jnp.asarray(rows))
                object.__setattr__(m, n, rb)
                continue
            stacked = np.asarray(jax.device_get(states[n]))[devs]
            if red == "sum":
                merged = stacked.sum(axis=0)
            elif red == "mean":
                merged = stacked.mean(axis=0)
            elif red == "max":
                merged = stacked.max(axis=0)
            elif red == "min":
                merged = stacked.min(axis=0)
            else:  # None — gather-stack; validate_reductions admitted nothing else
                gathered[n] = jnp.asarray(stacked)
                continue
            object.__setattr__(m, n, jnp.asarray(merged))
        if gathered:
            # gather states have no per-state reduction: either the class
            # folds its own gathered moment sets back into local form
            # (PearsonCorrCoef's `_fold_gathered_states` parallel-variance
            # merge), or the stacked (D, *s) form binds as-is — exactly the
            # eager post-sync state shape its compute already consumes
            fold = getattr(m, "_fold_gathered_states", None)
            if callable(fold):
                gathered = fold(gathered)
            for n, v in gathered.items():
                object.__setattr__(m, n, jnp.asarray(v))
        m._update_count = self._steps * len(devs)
        m._computed = None

    def sync_to_target(self) -> Any:
        """Populate the host target from the device states (reduction-merged).

        A host-side escape hatch (one ``device_get`` per state): after it,
        ``target.compute()``/``state_dict()`` observe the stream so far. The
        engine keeps streaming on its device states — this is a read, not a
        hand-over.
        """
        if self._units is not None and self._states is not None:
            for unit in self._units:
                self._fold_unit_to_host(unit)
            if self._collection is not None:
                self._collection._sync_compute_groups()
        return self.target

    # ----------------------------------------------------------- preparation
    def _prepare(self, args: tuple, kwargs: Dict[str, Any]) -> None:
        from copy import deepcopy

        probe = None
        if self._collection is not None or any(
            isinstance(getattr(m, n), RingBuffer)
            for m in ([self.target] if self._collection is None else self.target._modules.values())
            for n in m._defaults
        ):
            # one shard-sized eager probe on a throwaway clone: learns ring
            # row shapes, and for collections forms the compute groups the
            # fused step shares (group detection needs post-update states)
            probe = deepcopy(self.target)
            # 0-d leaves pass through unsliced: step() right after this probe
            # rejects them with the user-facing leading-axis message instead
            # of an IndexError from inside the probe
            shard_args, shard_kwargs = jax.tree_util.tree_map(
                lambda x: x[: max(1, x.shape[0] // self.world)]
                if _is_array(x) and getattr(x, "ndim", 0) >= 1
                else x,
                (args, kwargs),
            )
            probe.update(*shard_args, **shard_kwargs)

        units: List[_Unit] = []
        if self._collection is not None:
            groups = probe._groups  # formed by the probe update
            # adopt the probe's grouping: heads drive the fused step, members
            # rebind from their head at fold boundaries
            self._collection._groups = {i: list(g) for i, g in groups.items()}
            self._collection._groups_checked = True
            for g in groups.values():
                head_key = g[0]
                head = self.target._modules[head_key]
                members = [(name, self.target._modules[name]) for name in g]
                units.append(self._make_unit(head_key, head, members, probe._modules[head_key]))
        else:
            units.append(self._make_unit("", self.target, [("", self.target)], probe))

        # resilience: structure digest checked once, at trace time
        self._handshake_at_trace(units)
        if self._degraded:
            return

        self._units = units

        def ring_default(unit: _Unit, n: str) -> Dict[str, Any]:
            row_shape, row_dtype = unit.ring_rows[n]  # learned from the probe
            cap = unit.rings[n]
            return {
                "data": np.zeros((self.world, cap, *row_shape), row_dtype),
                "valid": np.zeros((self.world, cap), bool),
                "count": np.zeros((self.world,), np.int32),
            }

        self._install_stacked_defaults(units, ring_default)
        self._states = jax.tree_util.tree_map(
            lambda d: jax.device_put(d, self._sharding), self._stacked_defaults
        )
        if _OBS.enabled:
            per_device = self.predicted_device_bytes()
            if per_device is not None:
                # per-device scaling law: each device holds ONE replica row of
                # every registered state, so predicted per-device bytes = F
                # (the class's closed-form formula), independent of mesh size
                _telemetry_for(self.target).set_gauge(
                    "predicted_state_bytes|scope=spmd_device", per_device
                )

    def predicted_device_bytes(self) -> Optional[float]:
        """Closed-form predicted state bytes PER DEVICE, or ``None``.

        Resolved from the static memory cost model (``memory.json``) on the
        template instance(s). ``None`` when the model makes no exact finite
        claim (absent entry, opaque verdict, or an unbounded cat-list
        without ``cat_state_capacity``) — the telemetry gauge stands down
        rather than publish a guess.
        """
        from torchmetrics_tpu.collections import MetricCollection

        metrics = (
            list(self.target._modules.values())
            if isinstance(self.target, MetricCollection)
            else [self.target]
        )
        total = 0.0
        for m in metrics:
            pred = predicted_state_bytes(m)
            if pred is None or not pred.exact or pred.bytes == float("inf"):
                return None
            total += pred.bytes
        return total

    def _install_stacked_defaults(self, units: List[_Unit], ring_default: Any) -> None:
        """Build ``_stacked_defaults`` + the flat ``_defaults`` mirror.

        ``ring_default(unit, name)`` supplies one ring state's stacked
        zero-leaves — row shapes come from the probe on the fresh path and
        from the restored leaves on the restore path; everything else is
        identical and must STAY identical (a layout change in one path would
        make snapshot restore silently diverge from the fresh stream).
        """
        self._stacked_defaults = {}
        self._defaults = {}
        for unit in units:
            defaults: Dict[str, Any] = {}
            for n in unit.names:
                if n in unit.rings:
                    defaults[n] = ring_default(unit, n)
                else:
                    defaults[n] = stack_default(unit.metric._defaults[n], self.world)
            self._stacked_defaults[unit.key] = defaults
            pre = f"{unit.key}." if unit.key else ""
            for n in unit.names:
                if n in unit.rings:
                    for part in ("data", "valid", "count"):
                        self._defaults[f"{pre}{n}#{part}"] = defaults[n][part]
                else:
                    self._defaults[f"{pre}{n}"] = defaults[n]

    def _make_unit(self, key: str, metric: Any, members: List[Tuple[str, Any]], probe: Any) -> _Unit:
        names = list(metric._defaults)
        rings: Dict[str, int] = {}
        ring_rows: Dict[str, Tuple[tuple, Any]] = {}
        for n in names:
            state = getattr(metric, n)
            if isinstance(state, RingBuffer):
                rings[n] = state.capacity
                warmed = getattr(probe, n) if probe is not None else None
                if warmed is None or not isinstance(warmed, RingBuffer) or not warmed.initialized:
                    raise TorchMetricsUserError(
                        f"ring state `{n}` row shape could not be learned from the first batch"
                    )
                ring_rows[n] = (tuple(int(s) for s in warmed.data.shape[1:]), warmed.data.dtype)
        unit = _Unit(key=key, metric=metric, members=members, names=names, rings=rings)
        unit.ring_rows = ring_rows  # type: ignore[attr-defined]
        return unit

    def _handshake_at_trace(self, units: List[_Unit]) -> None:
        from torchmetrics_tpu._resilience.guard import handshake_at_trace

        for unit in units:
            if not handshake_at_trace(unit.metric):
                # transport degraded during the handshake: never compile —
                # the eager guarded path owns the stream from the start
                self._degrade("trace-time structure handshake degraded")
                return

    # ----------------------------------------------------------- compilation
    def _traced_unit_step(self, unit: _Unit, states: Dict[str, Any], a: tuple, kw: Dict[str, Any]):
        """(new local states, per-member values) for one unit, under trace."""
        from torchmetrics_tpu.metric import _squeeze_if_scalar

        m = unit.metric
        local = {}
        for n in unit.names:
            if n in unit.rings:
                s = states[n]
                local[n] = RingBuffer(
                    unit.rings[n], _data=s["data"][0], _valid=s["valid"][0], _count=s["count"][0]
                )
            else:
                local[n] = states[n][0]
        kw_m = m._filter_kwargs(**kw) if kw else kw
        new_local = m._traced_update(unit.names, local, a, kw_m)
        synced = sync_in_jit(
            {n: new_local[n] for n in unit.names},
            {n: m._reductions[n] for n in unit.names},
            self.axis_name,
            axis_index_groups=self.groups,
        )
        values = {}
        for name, member in unit.members:
            values[name] = _squeeze_if_scalar(member._traced_compute(unit.names, synced))
        out_states: Dict[str, Any] = {}
        for n in unit.names:
            v = new_local[n]
            if isinstance(v, RingBuffer):
                out_states[n] = {"data": v.data[None], "valid": v.valid[None], "count": v.count[None]}
            else:
                out_states[n] = v[None]
        return out_states, values

    def _build_step(self, treedef: Any, statics: Any, n_dyn: int):
        from torchmetrics_tpu.metric import Metric

        units = self._units

        def local_step(states, dyn):
            a, kw = Metric._merge_batch_args(treedef, list(dyn), statics)
            new_states: Dict[str, Dict[str, Any]] = {}
            values: Dict[str, Any] = {}
            for unit in units:
                out, vals = self._traced_unit_step(unit, states[unit.key], a, kw)
                new_states[unit.key] = out
                if self._collection is None:
                    values = vals[""]
                else:
                    values.update(vals)
            if self.groups is not None:
                # each shard's value is group-local: stack them over the axis
                # so the host slices one synced value per replica group
                values = jax.tree_util.tree_map(lambda v: v[None], values)
            return new_states, values

        specs = {u.key: state_specs(u.names, self.axis_name) for u in units}
        dyn_specs = [PartitionSpec(self.axis_name) for _ in range(n_dyn)]
        value_spec = PartitionSpec() if self.groups is None else PartitionSpec(self.axis_name)
        mapped = shard_map(
            local_step,
            mesh=self.mesh,
            in_specs=(specs, dyn_specs),
            out_specs=(specs, value_spec),
            check_vma=False,
        )
        return jax.jit(mapped, donate_argnums=(0,) if self.donate else ())

    def _build_compute(self):
        from torchmetrics_tpu.metric import _squeeze_if_scalar

        units = self._units

        def local_compute(states):
            values: Dict[str, Any] = {}
            for unit in units:
                m = unit.metric
                local = {}
                for n in unit.names:
                    if n in unit.rings:
                        s = states[unit.key][n]
                        local[n] = RingBuffer(
                            unit.rings[n], _data=s["data"][0], _valid=s["valid"][0], _count=s["count"][0]
                        )
                    else:
                        local[n] = states[unit.key][n][0]
                synced = sync_in_jit(
                    local, {n: m._reductions[n] for n in unit.names}, self.axis_name,
                    axis_index_groups=self.groups,
                )
                for name, member in unit.members:
                    values[name] = _squeeze_if_scalar(member._traced_compute(unit.names, synced))
            if self.groups is not None:
                # group-local values: stack over the axis so the host can
                # slice one synced value per replica group
                values = jax.tree_util.tree_map(lambda v: v[None], values)
            if self._collection is None:
                return values[""]
            return values

        specs = {u.key: state_specs(u.names, self.axis_name) for u in units}
        value_spec = PartitionSpec() if self.groups is None else PartitionSpec(self.axis_name)
        return jax.jit(
            shard_map(
                local_compute,
                mesh=self.mesh,
                in_specs=(specs,),
                out_specs=value_spec,
                check_vma=False,
            )
        )

    # ----------------------------------------------- snapshot/restore surface
    def state_dict(
        self,
        destination: Optional[Dict] = None,
        prefix: str = "",
        keep_vars: bool = False,
        integrity: bool = False,
        all_states: bool = False,
    ) -> Dict:
        """Host-numpy copy of the donated device states (``device_get``).

        The SnapshotManager calls this at snapshot boundaries; between
        boundaries the states never leave the device. The reserved
        ``{prefix}#spmd`` block records the mesh/unit skeleton so a fresh
        engine (same mesh size) can restore without having seen a batch.
        """
        if self._units is None or self._states is None:
            raise TorchMetricsUserError(
                "SpmdEngine has no device states yet (no step() has run)"
            )
        destination = {} if destination is None else destination
        keys: List[str] = []
        for unit in self._units:
            pre = f"{unit.key}." if unit.key else ""
            states = self._states[unit.key]
            for n in unit.names:
                if n in unit.rings:
                    st = jax.device_get(states[n])
                    for part in ("data", "valid", "count"):
                        k = f"{pre}{n}#{part}"
                        destination[prefix + k] = np.asarray(st[part])
                        keys.append(k)
                else:
                    k = f"{pre}{n}"
                    destination[prefix + k] = np.asarray(jax.device_get(states[n]))
                    keys.append(k)
        destination[prefix + "#spmd"] = {
            "world": self.world,
            "axis": self.axis_name,
            "groups": None if self.groups is None else [list(g) for g in self.groups],
            "units": [
                {
                    "key": u.key,
                    "members": [name for name, _ in u.members],
                    "names": list(u.names),
                    "rings": dict(u.rings),
                }
                for u in self._units
            ],
        }
        if integrity:
            from torchmetrics_tpu._resilience.integrity import attach_integrity

            attach_integrity(destination, keys, prefix, type(self).__name__)
        return destination

    def load_state_dict(self, state_dict: Dict, strict: Any = True, prefix: str = "") -> None:
        """Re-place checkpointed stacked states onto the mesh (same world size)."""
        from torchmetrics_tpu._resilience import integrity as _integrity

        meta = state_dict.get(_integrity.integrity_key(prefix))
        if meta is not None:
            corrupted = _integrity.verify_states(
                state_dict, prefix, meta, type(self).__name__, include_missing=strict is not False
            )
            if corrupted:
                _integrity.raise_corrupted(type(self).__name__, corrupted)
        blk = state_dict.get(prefix + "#spmd")
        if blk is None:
            raise TorchMetricsUserError("checkpoint lacks the `#spmd` block (not an SpmdEngine snapshot)")
        if int(blk["world"]) != self.world or blk["axis"] != self.axis_name:
            raise TorchMetricsUserError(
                f"snapshot was taken on a {blk['world']}-device `{blk['axis']}` mesh; this engine"
                f" runs {self.world}-device `{self.axis_name}` — donated states restore only onto"
                " an identical mesh layout"
            )
        snap_groups = blk.get("groups")
        live_groups = None if self.groups is None else [list(g) for g in self.groups]
        if snap_groups != live_groups:
            raise TorchMetricsUserError(
                f"snapshot was taken with axis_index_groups={snap_groups!r}; this engine runs"
                f" {live_groups!r} — per-group replica accumulation only restores onto the"
                " same group partition"
            )
        if self._units is None:
            self._rebuild_units(blk)
        states: Dict[str, Dict[str, Any]] = {}
        for unit in self._units:
            pre = f"{unit.key}." if unit.key else ""
            ustates: Dict[str, Any] = {}
            for n in unit.names:
                if n in unit.rings:
                    ustates[n] = {
                        part: jax.device_put(
                            jnp.asarray(state_dict[f"{prefix}{pre}{n}#{part}"]), self._sharding
                        )
                        for part in ("data", "valid", "count")
                    }
                else:
                    ustates[n] = jax.device_put(
                        jnp.asarray(state_dict[f"{prefix}{pre}{n}"]), self._sharding
                    )
            states[unit.key] = ustates
        self._states = states
        if self._stacked_defaults is None:
            # a pre-first-batch restore skipped _prepare: derive the stacked
            # defaults now (plain states from the metric's registered
            # defaults, ring shapes from the restored leaves) so reset()
            # has something to reset TO

            def ring_default(unit: _Unit, n: str) -> Dict[str, Any]:
                data = np.asarray(jax.device_get(states[unit.key][n]["data"]))
                return {
                    "data": np.zeros_like(data),
                    "valid": np.zeros(data.shape[:2], bool),
                    "count": np.zeros((self.world,), np.int32),
                }

            self._install_stacked_defaults(self._units, ring_default)

    def _rebuild_units(self, blk: Dict[str, Any]) -> None:
        """Unit skeleton from a checkpoint's ``#spmd`` block (pre-first-batch restore)."""
        units: List[_Unit] = []
        for u in blk["units"]:
            key = u["key"]
            metric = self.target._modules[key] if self._collection is not None else self.target
            members = (
                [(name, self.target._modules[name]) for name in u["members"]]
                if self._collection is not None
                else [("", self.target)]
            )
            units.append(
                _Unit(key=key, metric=metric, members=members, names=list(u["names"]), rings=dict(u["rings"]))
            )
        if self._collection is not None:
            self._collection._groups = {i: list(u["members"]) for i, u in enumerate(blk["units"])}
            self._collection._groups_checked = True
        self._units = units
        # stacked defaults are derived by load_state_dict once the restored
        # leaves are in hand (ring row shapes come from them)
        self._stacked_defaults = None
