"""SPMD in-graph metric engine (README "SPMD engine", ROADMAP item 1).

Metric states as sharded pytrees with explicit ``PartitionSpec``s over a
named mesh; update + cross-device sync + compute lowered to ONE donated
compiled step whose reductions come from each state's declared
``dist_reduce_fx`` as in-graph collectives. Gated by the eligibility
manifest's ``in_graph_sync`` facet; wrapped by the resilience handshake and
degradation; observable through the telemetry registry; durable through the
SnapshotManager's boundary ``device_get``.

Entry points: :class:`SpmdEngine` (or the ``Metric.to_spmd()`` /
``MetricCollection.to_spmd()`` conveniences).
"""

from torchmetrics_tpu._spmd.engine import SpmdEngine
from torchmetrics_tpu._spmd.specs import (
    COLLECTIVE_FOR,
    InGraphSyncUnsupported,
    build_mesh,
    state_specs,
    sync_plan,
    validate_reductions,
)

__all__ = [
    "COLLECTIVE_FOR",
    "InGraphSyncUnsupported",
    "SpmdEngine",
    "build_mesh",
    "state_specs",
    "sync_plan",
    "validate_reductions",
]
