"""MetricCollection with automatic compute groups.

Parity target: reference ``torchmetrics/collections.py`` (661 LoC). TPU-first
notes:

- States are immutable ``jax.Array`` leaves, so the reference's deep-copy-on-
  access dance (``collections.py:515-550``, guarding against user mutation of
  aliased states) is unnecessary: "aliasing" member states to the group head is
  just rebinding attribute references, and no copy is ever needed.
- Compute-group detection keeps the reference's behavior (first update runs all
  metrics, then states are pairwise compared shape+allclose and groups merged
  until fixpoint), after which only the group head's ``update`` runs and member
  states are rebound from the head.
"""

from __future__ import annotations

from collections import OrderedDict
from copy import deepcopy
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp

from torchmetrics_tpu._observability import tracing as _obs_trace
from torchmetrics_tpu._observability.state import OBS as _OBS
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.ringbuffer import RingBuffer

__all__ = ["MetricCollection"]


def _state_equal(a: Any, b: Any) -> bool:
    if isinstance(a, list) and isinstance(b, list):
        return len(a) == len(b) and all(_state_equal(x, y) for x, y in zip(a, b))
    if isinstance(a, list) != isinstance(b, list):
        return False
    if isinstance(a, RingBuffer) or isinstance(b, RingBuffer):
        if not (isinstance(a, RingBuffer) and isinstance(b, RingBuffer)):
            return False
        if a.capacity != b.capacity or len(a) != len(b):
            return False
        return len(a) == 0 or _state_equal(a.values(), b.values())
    a, b = jnp.asarray(a), jnp.asarray(b)
    if a.shape != b.shape or a.dtype != b.dtype:
        return False
    return bool(jnp.allclose(a.astype(jnp.float32), b.astype(jnp.float32)))


class MetricCollection:
    """Dict-like container fanning update/compute over many metrics.

    Reference ``collections.py:34``. Accepts a single metric, a sequence,
    a mapping, or nested collections.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MetricCollection
        >>> from torchmetrics_tpu.classification import MulticlassAccuracy, MulticlassPrecision
        >>> mc = MetricCollection([MulticlassAccuracy(num_classes=3), MulticlassPrecision(num_classes=3)])
        >>> preds = jnp.array([0, 2, 1]); target = jnp.array([0, 1, 1])
        >>> out = mc(preds, target)
        >>> sorted(out.keys())
        ['MulticlassAccuracy', 'MulticlassPrecision']
    """

    def __init__(
        self,
        metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]],
        *additional_metrics: Metric,
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
        compute_groups: Union[bool, List[List[str]]] = True,
    ) -> None:
        self._modules: "OrderedDict[str, Metric]" = OrderedDict()
        self.prefix = self._check_arg(prefix, "prefix")
        self.postfix = self._check_arg(postfix, "postfix")
        self._enable_compute_groups = compute_groups
        self._groups_checked = False
        self._state_is_copy = False
        self._groups: Dict[int, List[str]] = {}
        # collection-level update-journal hook: one SnapshotManager attached
        # here journals whole-collection updates (members stay hook-free, so
        # nothing is double-journaled)
        self._snapshot_hook: Optional[Any] = None

        self.add_metrics(metrics, *additional_metrics)

    # ------------------------------------------------------------- construction
    @staticmethod
    def _check_arg(arg: Optional[str], name: str) -> Optional[str]:
        if arg is None or isinstance(arg, str):
            return arg
        raise ValueError(f"Expected input `{name}` to be a string, but got {type(arg)}")

    def add_metrics(
        self, metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]], *additional_metrics: Metric
    ) -> None:
        """Add metrics to the collection (reference ``collections.py:389-454``)."""
        if isinstance(metrics, Metric):
            metrics = [metrics]
        if isinstance(metrics, Sequence):
            metrics = list(metrics)
            remain: list = []
            for m in additional_metrics:
                (metrics if isinstance(m, Metric) else remain).append(m)
            if remain:
                raise ValueError(f"You have passed extra arguments {remain} which are not `Metric`.")
        elif additional_metrics:
            raise ValueError(
                f"You have passes extra arguments {additional_metrics} which are not compatible"
                f" with first passed dictionary {metrics} so they will be ignored."
            )

        if isinstance(metrics, dict):
            for name in sorted(metrics.keys()):
                metric = metrics[name]
                if not isinstance(metric, (Metric, MetricCollection)):
                    raise ValueError(
                        f"Value {metric} belonging to key {name} is not an instance of"
                        " `Metric` or `MetricCollection`"
                    )
                if isinstance(metric, Metric):
                    self._modules[name] = metric
                else:
                    for k, v in metric.items(keep_base=False):
                        self._modules[f"{name}_{k}"] = v
        elif isinstance(metrics, Sequence):
            for metric in metrics:
                if isinstance(metric, MetricCollection):
                    for name, m in metric.items(keep_base=False):
                        if name in self._modules:
                            raise ValueError(f"Encountered two metrics both named {name}")
                        self._modules[name] = m
                elif isinstance(metric, Metric):
                    name = metric.__class__.__name__
                    if name in self._modules:
                        raise ValueError(f"Encountered two metrics both named {name}")
                    self._modules[name] = metric
                else:
                    raise ValueError(f"Input {metric} to `MetricCollection` is not a instance of `Metric`")
        else:
            raise ValueError(
                "Unknown input to MetricCollection. Expected a `Metric`, sequence of `Metric`s, or a dict."
            )
        self._groups_checked = False

    # ------------------------------------------------------------------ update
    def update(self, *args: Any, **kwargs: Any) -> None:
        """Update each metric (group heads only once groups are formed)."""
        # the collection span parents every member metric's update span, so
        # one fan-out call stays one causally-ordered request tree
        _sp = _obs_trace.begin_span("update", "MetricCollection") if _OBS.tracing else None
        _sp_err: Optional[BaseException] = None
        try:
            if self._groups_checked:
                for cg in self._groups.values():
                    head = self._modules[cg[0]]
                    head.update(*args, **head._filter_kwargs(**kwargs))
                self._sync_compute_groups()
            else:
                for m in self._modules.values():
                    m.update(*args, **m._filter_kwargs(**kwargs))
                if self._enable_compute_groups:
                    self._merge_compute_groups()
                else:
                    self._groups = {i: [name] for i, name in enumerate(self._modules)}
                    self._groups_checked = True
        except BaseException as err:
            _sp_err = err
            raise
        finally:
            if _sp is not None:
                _obs_trace.end_span(_sp, _sp_err)
        self._journal_record("update", args, kwargs)

    def _journal_record(self, method: str, args: tuple, kwargs: Dict[str, Any]) -> None:
        """Feed one completed collection-wide update to the SnapshotManager.

        Fires after every member (or group head + state rebind) committed,
        so a snapshot triggered here always captures a mutually consistent
        member-state set.
        """
        hook = self.__dict__.get("_snapshot_hook")
        if hook is not None:
            hook.record(self, method, args, kwargs)

    def precompile(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Warm every member's compiled default update path (``Metric.precompile``).

        Fans the example batch out exactly as :meth:`update` does (per-member
        kwarg filtering), so the executables built — or loaded from the AOT
        cache — match the signatures real traffic will dispatch. Member
        states are untouched. Returns ``{member_name: report}``.
        """
        return {
            name: m.precompile(*args, **m._filter_kwargs(**kwargs))
            for name, m in self._modules.items()
        }

    def _merge_compute_groups(self) -> None:
        """Pairwise-merge metrics whose states are identical (reference ``collections.py:228-262``)."""
        if isinstance(self._enable_compute_groups, list):
            self._groups = {i: [str(n) for n in g] for i, g in enumerate(self._enable_compute_groups)}
            grouped = {n for g in self._groups.values() for n in g}
            i = len(self._groups)
            for name in self._modules:
                if name not in grouped:
                    self._groups[i] = [name]
                    i += 1
            self._groups_checked = True
            return

        self._groups = {i: [name] for i, name in enumerate(self._modules)}
        while True:
            for cg_idx1, cg_members1 in deepcopy(self._groups).items():
                for cg_idx2, cg_members2 in deepcopy(self._groups).items():
                    if cg_idx1 == cg_idx2:
                        continue
                    m1 = self._modules[cg_members1[0]]
                    m2 = self._modules[cg_members2[0]]
                    if self._equal_metric_states(m1, m2):
                        self._groups[cg_idx1].extend(self._groups.pop(cg_idx2))
                        break
                else:
                    continue
                break
            else:
                break
        self._groups = {i: g for i, g in enumerate(self._groups.values())}
        self._groups_checked = True

    @staticmethod
    def _equal_metric_states(metric1: Metric, metric2: Metric) -> bool:
        """Shape + allclose comparison of two metrics' states (reference ``collections.py:264-287``)."""
        if not metric1._defaults or not metric2._defaults:
            return False
        if metric1._defaults.keys() != metric2._defaults.keys():
            return False
        if metric1._update_count != metric2._update_count:
            return False
        return all(_state_equal(getattr(metric1, k), getattr(metric2, k)) for k in metric1._defaults)

    def _sync_compute_groups(self) -> None:
        """Rebind member states from their group head (immutable arrays → no copies)."""
        for cg in self._groups.values():
            head = self._modules[cg[0]]
            for name in cg[1:]:
                member = self._modules[name]
                for attr in head._defaults:
                    state = getattr(head, attr)
                    if isinstance(state, RingBuffer):
                        # mutable container: members need their own copy, or the
                        # next update would append once per aliased member
                        setattr(member, attr, state.copy())
                    else:
                        setattr(member, attr, list(state) if isinstance(state, list) else state)
                member._update_count = head._update_count
                member._computed = None

    # ----------------------------------------------------------------- compute
    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Per-batch value from every metric while accumulating global state."""
        _sp = _obs_trace.begin_span("forward", "MetricCollection") if _OBS.tracing else None
        _sp_err: Optional[BaseException] = None
        try:
            res = {name: m(*args, **m._filter_kwargs(**kwargs)) for name, m in self._modules.items()}
            if not self._groups_checked and self._enable_compute_groups:
                self._merge_compute_groups()
        except BaseException as err:
            _sp_err = err
            raise
        finally:
            if _sp is not None:
                _obs_trace.end_span(_sp, _sp_err)
        # forward and update produce the same accumulated state, so the
        # journal replays either through collection.update()
        self._journal_record("update", args, kwargs)
        return self._flatten_results(res)

    def compute(self) -> Dict[str, Any]:
        _sp = _obs_trace.begin_span("compute", "MetricCollection") if _OBS.tracing else None
        _sp_err: Optional[BaseException] = None
        try:
            if self._groups_checked:
                self._sync_compute_groups()
            res = {name: m.compute() for name, m in self._modules.items()}
        except BaseException as err:
            _sp_err = err
            raise
        finally:
            if _sp is not None:
                _obs_trace.end_span(_sp, _sp_err)
        return self._flatten_results(res)

    def _flatten_results(self, res: Dict[str, Any]) -> Dict[str, Any]:
        """Flatten dict-valued results and apply prefix/postfix (reference ``collections.py:314-359``)."""
        out: Dict[str, Any] = {}
        for name, value in res.items():
            if isinstance(value, dict):
                for k, v in value.items():
                    if k in res or k in out:
                        k = f"{name}_{k}"
                    out[k] = v
            else:
                out[name] = value
        return {self._set_name(k): v for k, v in out.items()}

    def _set_name(self, base: str) -> str:
        name = base if self.prefix is None else self.prefix + base
        return name if self.postfix is None else name + self.postfix

    def __call__(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return self.forward(*args, **kwargs)

    # -------------------------------------------------------------- maintenance
    def reset(self) -> None:
        # a member's reset() may surface its pending deferred violation
        # (clear-then-raise): every member must still get reset, so one
        # collection.reset() call both cleans everything and raises the
        # first violation — not one call per violating member
        pending: Optional[BaseException] = None
        for m in self._modules.values():
            try:
                m.reset()
            except RuntimeError as err:
                pending = pending or err
        # journaled for the same reason as Metric.reset: restore must not
        # resurrect accumulation a mid-stream reset discarded
        self._journal_record("reset", (), {})
        if pending is not None:
            raise pending

    def clone(self, prefix: Optional[str] = None, postfix: Optional[str] = None) -> "MetricCollection":
        mc = deepcopy(self)
        if prefix is not None:
            mc.prefix = self._check_arg(prefix, "prefix")
        if postfix is not None:
            mc.postfix = self._check_arg(postfix, "postfix")
        return mc

    def persistent(self, mode: bool = True) -> None:
        for m in self._modules.values():
            m.persistent(mode)

    def state_dict(self, prefix: str = "", integrity: bool = False, all_states: bool = False) -> Dict[str, Any]:
        destination: Dict[str, Any] = {}
        for name, m in self._modules.items():
            m.state_dict(destination, prefix=f"{prefix}{name}.", integrity=integrity, all_states=all_states)
        return destination

    def load_state_dict(
        self, state_dict: Dict[str, Any], strict: Union[bool, str] = True, prefix: str = ""
    ) -> None:
        """Restore member states; ``strict="repair"`` resets corrupted states only.

        Each member verifies its own integrity block (when present) under its
        ``{prefix}{name}.`` namespace. Verification of ALL members runs
        before ANY member loads, so a corrupted later member cannot leave the
        collection half-restored: either the whole load proceeds (repairing
        under ``strict="repair"``) or it raises with every member untouched.
        """
        from torchmetrics_tpu._resilience import integrity as _integrity

        if strict != "repair":
            corrupted_all: Dict[str, str] = {}
            for name, m in self._modules.items():
                member_prefix = f"{prefix}{name}."
                meta = state_dict.get(_integrity.integrity_key(member_prefix))
                if meta is not None:
                    bad = _integrity.verify_states(
                        state_dict, member_prefix, meta, type(m).__name__,
                        include_missing=strict is not False,
                    )
                    corrupted_all.update({f"{name}.{k}": v for k, v in bad.items()})
            if corrupted_all:
                _integrity.raise_corrupted(f"MetricCollection(prefix={prefix!r})", corrupted_all)
            # the pre-pass hashed every state: members skip re-verification
            for name, m in self._modules.items():
                m.load_state_dict(state_dict, strict=strict, prefix=f"{prefix}{name}.", _verified=True)
            self._journal_record("external", (), {})
            return
        # repair mode: member verification never raises EXCEPT on an unknown
        # schema version — validate every block up front so a bad block on a
        # later member cannot abort the loop after earlier members loaded
        for name, m in self._modules.items():
            meta = state_dict.get(_integrity.integrity_key(f"{prefix}{name}."))
            if meta is not None:
                _integrity.validate_version(meta, type(m).__name__)
        for name, m in self._modules.items():
            m.load_state_dict(state_dict, strict=strict, prefix=f"{prefix}{name}.")
        # mid-stream manual load: anchor the un-journalable transition
        self._journal_record("external", (), {})

    def merge_state(self, incoming: "MetricCollection") -> None:
        """Merge another collection's state member-wise (fleet rollup seam).

        Both collections must hold the same member names with the same
        metric types; every member merge uses ``Metric.merge_state`` (the
        declared per-state reductions), so a collection folds across hosts
        exactly like its members would individually. Validation runs before
        any member merges — a mismatch leaves this collection untouched.
        """
        from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

        if not isinstance(incoming, MetricCollection):
            raise TorchMetricsUserError(
                f"MetricCollection.merge_state needs a MetricCollection, got {type(incoming).__name__}"
            )
        if set(incoming._modules) != set(self._modules):
            missing = sorted(set(self._modules) ^ set(incoming._modules))
            raise TorchMetricsUserError(
                f"Cannot merge MetricCollections with different members (mismatched: {missing})"
            )
        for name, m in self._modules.items():
            other = incoming._modules[name]
            if type(other) is not type(m):
                raise TorchMetricsUserError(
                    f"Cannot merge member {name!r}: {type(other).__name__} into {type(m).__name__}"
                )
        for name, m in self._modules.items():
            m.merge_state(incoming._modules[name])

    # ------------------------------------------------------------- resilience
    def set_resilience_policy(self, **kwargs: Any) -> "MetricCollection":
        """Fan a resilience-policy change out to every member metric.

        Accepts the same keyword arguments as ``Metric.set_resilience_policy``
        (``sync_policy``, ``nan_policy``); only the arguments passed change.
        Compute-group heads and members share policies, so degradation
        semantics stay uniform within a group.
        """
        for m in self._modules.values():
            m.set_resilience_policy(**kwargs)
        return self

    def resilience_report(self) -> Dict[str, Any]:
        """Per-member resilience reports, keyed like :meth:`compute` results."""
        return {self._set_name(name): m.resilience_report() for name, m in self._modules.items()}

    # ------------------------------------------------------------- telemetry
    def telemetry_report(self, aggregate: bool = False) -> Any:
        """Runtime telemetry for the collection (OBSERVABILITY.md).

        With ``aggregate=False`` (default) returns per-member
        :class:`~torchmetrics_tpu._observability.telemetry.TelemetryReport`
        objects keyed like :meth:`compute` results. With ``aggregate=True``
        returns ONE merged report whose counters sum every member — the
        shape a scrape/log line wants for "how is this eval suite behaving".
        Note that with compute groups active only group heads execute
        ``update``, so member path-counters reflect the runtime's actual
        dispatch, not the logical metric count.
        """
        reports = {self._set_name(name): m.telemetry_report() for name, m in self._modules.items()}
        if self.__dict__.get("_telem") is not None:
            # a collection-level SnapshotManager attributes its snapshot/
            # journal/restore counters to the COLLECTION object — surface
            # them instead of silently dropping collection-level telemetry
            from torchmetrics_tpu._observability.telemetry import report_for

            reports["__collection__"] = report_for(self)
        if not aggregate:
            return reports
        from torchmetrics_tpu._observability.telemetry import TelemetryReport

        return TelemetryReport.merged(list(reports.values()), name="MetricCollection")

    def to_spmd(self, *, mesh: Any = None, axis_name: str = "dp", **kwargs: Any) -> Any:
        """Hand the (fresh) collection to the SPMD in-graph engine.

        Compute groups share ONE fused step: each group's head updates and
        syncs once in-graph, every member computes from the head's synced
        states inside the same executable, and ``step()`` returns a dict
        keyed like :meth:`compute`. Every member class must pass the
        eligibility manifest's ``in_graph_sync`` gate.
        """
        from torchmetrics_tpu._spmd import SpmdEngine

        return SpmdEngine(self, mesh=mesh, axis_name=axis_name, **kwargs)

    def to_stream_pool(self, *, capacity: int = 8, **kwargs: Any) -> Any:
        """N independent streams of this (fresh) collection, one vmapped step.

        Compute groups share stacked states: each group's head updates once
        per lane, every member computes from the head's slot rows inside the
        same compiled executable, and ``pool.compute(i)`` returns a dict
        keyed like :meth:`compute`. Every member class must pass the
        eligibility manifest's stream-pool gate. See STREAMS.md.
        """
        from torchmetrics_tpu._streams import StreamPool

        return StreamPool(self, capacity=capacity, **kwargs)

    def set_dtype(self, dst_type: Any) -> "MetricCollection":
        for m in self._modules.values():
            m.set_dtype(dst_type)
        return self

    def to_device(self, device: Any) -> "MetricCollection":
        for m in self._modules.values():
            m.to_device(device)
        return self

    def sync(self, **kwargs: Any) -> None:
        for m in self._modules.values():
            m.sync(**kwargs)

    def unsync(self, should_unsync: bool = True) -> None:
        for m in self._modules.values():
            m.unsync(should_unsync)

    @property
    def compute_groups(self) -> Dict[int, List[str]]:
        """Current compute-group assignment."""
        return self._groups

    # -------------------------------------------------------------- dict-like
    def items(self, keep_base: bool = False, copy_state: bool = True) -> Iterable[Tuple[str, Metric]]:
        if self._groups_checked:
            self._sync_compute_groups()
        if keep_base:
            return self._modules.items()
        return [(self._set_name(k), v) for k, v in self._modules.items()]

    def keys(self, keep_base: bool = False) -> Iterable[str]:
        if keep_base:
            return self._modules.keys()
        return [self._set_name(k) for k in self._modules]

    def values(self, copy_state: bool = True) -> Iterable[Metric]:
        if self._groups_checked:
            self._sync_compute_groups()
        return self._modules.values()

    def __getitem__(self, key: str) -> Metric:
        if self._groups_checked:
            self._sync_compute_groups()
        return self._modules[key]

    def __len__(self) -> int:
        return len(self._modules)

    def __iter__(self):
        return iter(self.keys())

    def __contains__(self, key: str) -> bool:
        return key in self._modules or key in self.keys()

    def __repr__(self) -> str:
        repr_str = self.__class__.__name__ + "("
        for name, m in self._modules.items():
            repr_str += f"\n  {name}: {m!r}"
        if self.prefix:
            repr_str += f"\n  prefix={self.prefix}"
        if self.postfix:
            repr_str += f"\n  postfix={self.postfix}"
        return repr_str + "\n)"

    # ---------------------------------------------------------------- plotting
    def plot(self, val: Optional[Any] = None, ax: Optional[Any] = None, together: bool = False):
        """Plot all collection members (reference ``collections.py:578-661``)."""
        val = val if val is not None else self.compute()
        if together:
            from torchmetrics_tpu.utilities.plot import plot_single_or_multi_val

            return plot_single_or_multi_val(val, ax=ax)
        return [m.plot(val[self._set_name(name)], ax=ax) for name, m in self._modules.items()]
