"""torchmetrics_tpu: a TPU-native (JAX/XLA/Pallas) metrics framework.

Same capability surface as TorchMetrics; designed from scratch for JAX — state
is immutable array pytrees, distributed sync is XLA collectives over a device
mesh, heavy kernels are jit-compiled XLA/Pallas.
"""

import logging as __logging

from torchmetrics_tpu.__about__ import __version__
from torchmetrics_tpu.metric import CompositionalMetric, Metric

_logger = __logging.getLogger("torchmetrics_tpu")
_logger.addHandler(__logging.StreamHandler())
_logger.setLevel(__logging.INFO)

from torchmetrics_tpu import classification, functional, utilities  # noqa: E402
from torchmetrics_tpu.classification import *  # noqa: F401,F403,E402
from torchmetrics_tpu.classification import __all__ as _classification_all  # noqa: E402

__all__ = [
    "CompositionalMetric",
    "Metric",
    "classification",
    "functional",
    "utilities",
    "__version__",
    *_classification_all,
]
