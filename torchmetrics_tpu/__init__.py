"""torchmetrics_tpu: a TPU-native (JAX/XLA/Pallas) metrics framework.

Same capability surface as TorchMetrics; designed from scratch for JAX — state
is immutable array pytrees, distributed sync is XLA collectives over a device
mesh, heavy kernels are jit-compiled XLA/Pallas.
"""

import logging as __logging

from torchmetrics_tpu.__about__ import __version__
from torchmetrics_tpu._aot import get_aot_cache, set_aot_cache
from torchmetrics_tpu.metric import CompositionalMetric, Metric

_logger = __logging.getLogger("torchmetrics_tpu")
_logger.addHandler(__logging.StreamHandler())
_logger.setLevel(__logging.INFO)

from torchmetrics_tpu import (  # noqa: E402
    aggregation,
    audio,
    classification,
    clustering,
    detection,
    functional,
    image,
    multimodal,
    nominal,
    regression,
    retrieval,
    text,
    utilities,
    wrappers,
)
from torchmetrics_tpu.detection import *  # noqa: F401,F403,E402
from torchmetrics_tpu.detection import __all__ as _detection_all  # noqa: E402
from torchmetrics_tpu.image import *  # noqa: F401,F403,E402
from torchmetrics_tpu.image import __all__ as _image_all  # noqa: E402
from torchmetrics_tpu.clustering import *  # noqa: F401,F403,E402
from torchmetrics_tpu.clustering import __all__ as _clustering_all  # noqa: E402
from torchmetrics_tpu.nominal import *  # noqa: F401,F403,E402
from torchmetrics_tpu.nominal import __all__ as _nominal_all  # noqa: E402
from torchmetrics_tpu.retrieval import *  # noqa: F401,F403,E402
from torchmetrics_tpu.retrieval import __all__ as _retrieval_all  # noqa: E402
from torchmetrics_tpu.audio import *  # noqa: F401,F403,E402
from torchmetrics_tpu.audio import __all__ as _audio_all  # noqa: E402
from torchmetrics_tpu.aggregation import *  # noqa: F401,F403,E402
from torchmetrics_tpu.aggregation import __all__ as _aggregation_all  # noqa: E402
from torchmetrics_tpu.classification import *  # noqa: F401,F403,E402
from torchmetrics_tpu.classification import __all__ as _classification_all  # noqa: E402
from torchmetrics_tpu.collections import MetricCollection  # noqa: E402
from torchmetrics_tpu.multimodal import *  # noqa: F401,F403,E402
from torchmetrics_tpu.multimodal import __all__ as _multimodal_all  # noqa: E402
from torchmetrics_tpu.regression import *  # noqa: F401,F403,E402
from torchmetrics_tpu.regression import __all__ as _regression_all  # noqa: E402
from torchmetrics_tpu.text import *  # noqa: F401,F403,E402
from torchmetrics_tpu.text import __all__ as _text_all  # noqa: E402
from torchmetrics_tpu.wrappers import *  # noqa: F401,F403,E402
from torchmetrics_tpu.wrappers import __all__ as _wrappers_all  # noqa: E402

__all__ = [
    "CompositionalMetric",
    "Metric",
    "MetricCollection",
    "aggregation",
    "audio",
    "classification",
    "clustering",
    "detection",
    "functional",
    "image",
    "multimodal",
    "nominal",
    "regression",
    "retrieval",
    "text",
    "utilities",
    "wrappers",
    "__version__",
    "get_aot_cache",
    "set_aot_cache",
    *_aggregation_all,
    *_audio_all,
    *_classification_all,
    *_clustering_all,
    *_detection_all,
    *_image_all,
    *_multimodal_all,
    *_nominal_all,
    *_regression_all,
    *_retrieval_all,
    *_text_all,
    *_wrappers_all,
]
