"""Aggregation primitives: Max/Min/Sum/Cat/Mean + running variants.

Parity target: reference ``torchmetrics/aggregation.py`` (727 LoC) — the
primitive aggregators built directly on the state DSL. TPU-first notes:

- NaN handling (``nan_strategy``) is dual-form: concrete (eager) arrays get
  the reference's exact raise/warn/filter behavior, while traced arrays get
  branchless neutral-imputation (``ignore`` becomes a zero-weight mask — the
  static-shape form of the boolean filtering) with the raise/warn side
  effects deferred through the fused-validation flags. Out-of-the-box
  aggregators therefore auto-compile (eligibility-prover round).
- ``MeanMetric`` keeps (weighted-sum, weight-sum) — both plain ``sum`` states,
  so the distributed merge is a single fused psum.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.checks import _is_concrete
from torchmetrics_tpu.utilities.data import dim_zero_cat
from torchmetrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array

__all__ = [
    "BaseAggregator",
    "MaxMetric",
    "MinMetric",
    "SumMetric",
    "CatMetric",
    "MeanMetric",
    "RunningMean",
    "RunningSum",
]


class BaseAggregator(Metric):
    """Base class for aggregation metrics (reference ``aggregation.py:30-113``).

    The NaN strategy runs in two equivalent forms: on concrete (eager)
    arrays it keeps the exact reference behavior — raise for ``"error"``,
    warn + dynamically drop NaN elements for ``"warn"``/``"ignore"`` — while
    under trace it imputes branchlessly (NaNs become the aggregator's neutral
    element with zero weight, which reduces identically to dropping). The
    raise/warn side effects ride the fused-validation flag vector
    (:meth:`_traced_value_flags`, severity ``"error"``/``"warn"``) and
    surface at the next host sync, so the out-of-the-box aggregators
    (``nan_strategy="warn"``) auto-compile instead of being pinned eager by
    the per-batch host NaN check.
    """

    is_differentiable = None
    higher_is_better = None
    full_state_update: bool = False

    # the value NaNs impute to under trace: a no-op for the reduction
    # (0 for sum/mean; Max/Min override with ∓inf)
    _nan_neutral: float = 0.0
    # CatMetric appends rows, so imputation would KEEP dropped elements —
    # it refuses the traced form and stays on the eager path
    _nan_imputation_traceable: bool = True

    def __init__(
        self,
        fn: Union[Callable, str],
        default_value: Union[Array, List],
        nan_strategy: Union[str, float] = "error",
        state_name: str = "value",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_nan_strategy = ("error", "warn", "ignore", "disable")
        if nan_strategy not in allowed_nan_strategy and not isinstance(nan_strategy, float):
            raise ValueError(
                f"Arg `nan_strategy` should either be a float or one of {allowed_nan_strategy}"
                f" but got {nan_strategy}."
            )
        self.nan_strategy = nan_strategy
        # raise/warn strategies carry a per-batch value check; declaring it
        # via validate_args opts the compiled path into the fused flag vector
        # ("ignore" and float imputation are pure value rewrites — no flags)
        self.validate_args = nan_strategy in ("error", "warn")
        self.add_state(state_name, default=default_value, dist_reduce_fx=fn)
        self.state_name = state_name

    def _traced_value_flags(self, value: Union[float, Array], weight: Optional[Union[float, Array]] = None):
        """Fused NaN check: one flag, severity matching the strategy."""
        x = jnp.asarray(value).astype(jnp.float32)
        bad = jnp.any(jnp.isnan(x))
        if weight is not None:
            bad = bad | jnp.any(jnp.isnan(jnp.asarray(weight, dtype=jnp.float32)))
        if self.nan_strategy == "error":
            return ("Encountered `nan` values in tensor",), bad[None], ("error",)
        return ("Encountered `nan` values in tensor. Will be removed.",), bad[None], ("warn",)

    def _cast_and_nan_check_input(
        self, x: Union[float, Array], weight: Optional[Union[float, Array]] = None
    ) -> Tuple[Array, Array]:
        """Convert input to float arrays and apply the NaN strategy."""
        x = jnp.asarray(x, dtype=jnp.float32) if not hasattr(x, "dtype") else jnp.asarray(x).astype(jnp.float32)
        weight_was_scalar = weight is None or jnp.ndim(weight) == 0
        if weight is not None:
            weight = jnp.asarray(weight, dtype=jnp.float32)
        else:
            weight = jnp.ones_like(x)
        weight = jnp.broadcast_to(weight, x.shape)

        if self.nan_strategy == "disable":
            return x, weight
        nans = jnp.isnan(x) | jnp.isnan(weight)
        concrete = _is_concrete(nans)
        if concrete and bool(jnp.any(nans)):
            # eager/concrete: exact reference behavior (raise, warn, true
            # dynamic filtering); float imputation falls through below
            if self.nan_strategy == "error":
                raise RuntimeError("Encountered `nan` values in tensor")
            if self.nan_strategy in ("ignore", "warn"):
                if self.nan_strategy == "warn":
                    rank_zero_warn("Encountered `nan` values in tensor. Will be removed.", UserWarning)
                keep = jnp.nonzero(~nans.reshape(-1))[0]
                return x.reshape(-1)[keep], weight.reshape(-1)[keep]
        if self.nan_strategy in ("error", "warn", "ignore"):
            if not concrete and not self._nan_imputation_traceable:
                from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

                raise TorchMetricsUserError(
                    f"{type(self).__name__} keeps dropped elements out of an append-mode state;"
                    " its NaN filtering is value-dependent and cannot trace"
                )
            if not concrete and self.nan_strategy == "error" and not self.__dict__.get("_fused_flags_tracing"):
                # a trace WITHOUT the fused-flag machinery (jit_update,
                # scan_update, external jit/vmap) has no way to raise-or-drop
                # on a NaN batch: silently imputing would commit a partial
                # batch the eager path refuses, so fail the trace loudly
                from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

                raise TorchMetricsUserError(
                    f"{type(self).__name__}(nan_strategy='error') cannot run under a trace without"
                    " the fused violation flags (plain `update()` auto-compiles them; `jit_update`/"
                    "`scan_update` skip validation): use nan_strategy='ignore'/'disable' or the"
                    " plain update path"
                )
            # branchless neutral imputation: reduces identically to dropping
            # ("error" batches are additionally dropped whole by the fused
            # flag on the compiled path, mirroring the eager raise)
            x = jnp.where(nans, self._nan_neutral, x)
            weight = jnp.where(nans, 0.0, weight)
            return x, weight
        x = jnp.where(nans, float(self.nan_strategy), x)
        if weight_was_scalar:
            # reference parity quirk: it broadcasts the scalar weight
            # BEFORE the nan check (aggregation.py:563), so its
            # in-place `weight[nans] = value` writes the one underlying
            # element through the 0-stride view and EVERY weight
            # becomes the replacement value (nan_strategy=0.0 thus
            # yields 0/0 = nan from MeanMetric) — but only when the batch
            # actually contains NaNs (jnp.where keeps this branchless)
            weight = jnp.where(
                jnp.any(nans), jnp.full_like(weight, float(self.nan_strategy)), weight
            )
        else:
            weight = jnp.where(nans, float(self.nan_strategy), weight)
        return x, weight

    def update(self, value: Union[float, Array]) -> None:
        """Overwrite in child class."""

    def compute(self) -> Array:
        return getattr(self, self.state_name)


class MaxMetric(BaseAggregator):
    """Running maximum of a stream of values (reference ``aggregation.py:114``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.aggregation import MaxMetric
        >>> metric = MaxMetric()
        >>> metric.update(1.0)
        >>> metric.update(jnp.array([2.0, 3.0]))
        >>> metric.compute()
        Array(3., dtype=float32)
    """

    full_state_update = True
    _nan_neutral = float("-inf")  # maximum(-inf, state) == state

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("max", jnp.array(-jnp.inf, dtype=jnp.float32), nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        if value.size:
            self.value = jnp.maximum(self.value, jnp.max(value))


class MinMetric(BaseAggregator):
    """Running minimum of a stream of values (reference ``aggregation.py:219``)."""

    full_state_update = True
    _nan_neutral = float("inf")  # minimum(inf, state) == state

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("min", jnp.array(jnp.inf, dtype=jnp.float32), nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        if value.size:
            self.value = jnp.minimum(self.value, jnp.min(value))


class SumMetric(BaseAggregator):
    """Running sum of a stream of values (reference ``aggregation.py:324``)."""

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", jnp.array(0.0, dtype=jnp.float32), nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        if value.size:
            self.value = self.value + jnp.sum(value)


class CatMetric(BaseAggregator):
    """Concatenate a stream of values (reference ``aggregation.py:429``)."""

    # appended rows would keep neutral-imputed elements that the eager path
    # truly drops: the traced NaN form is refused (metric stays eager)
    _nan_imputation_traceable = False

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("cat", [], nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        if value.size:
            self.value.append(value)

    def compute(self) -> Array:
        if isinstance(self.value, list) and self.value:
            return dim_zero_cat(self.value)
        return self.value


class MeanMetric(BaseAggregator):
    """Weighted running mean of a stream of values (reference ``aggregation.py:493``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.aggregation import MeanMetric
        >>> metric = MeanMetric()
        >>> metric.update(1.0)
        >>> metric.update(jnp.array([2.0, 3.0]))
        >>> metric.compute()
        Array(2., dtype=float32)
    """

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", jnp.array(0.0, dtype=jnp.float32), nan_strategy, **kwargs)
        self.add_state("weight", default=jnp.array(0.0, dtype=jnp.float32), dist_reduce_fx="sum")

    def update(self, value: Union[float, Array], weight: Union[float, Array] = 1.0) -> None:
        value, weight = self._cast_and_nan_check_input(value, weight)
        if value.size == 0:
            return
        self.value = self.value + jnp.sum(value * weight)
        self.weight = self.weight + jnp.sum(weight)

    def compute(self) -> Array:
        # raw division (reference aggregation.py:573): zero total weight —
        # e.g. the nan_strategy=0.0 broadcast-replacement quirk — yields nan
        return self.value / self.weight


def _make_running(name: str, base_cls: type, doc: str) -> type:
    from torchmetrics_tpu.wrappers.running import Running

    def __init__(self, window: int = 5, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        Running.__init__(self, base_cls(nan_strategy=nan_strategy, **kwargs), window=window)

    cls = type(name, (Running,), {"__init__": __init__, "__doc__": doc})
    cls.__module__ = __name__  # make the generated class picklable
    cls.__qualname__ = name
    return cls


RunningMean = _make_running(
    "RunningMean", MeanMetric, "Mean over the last ``window`` updates (reference ``aggregation.py:616``)."
)
RunningSum = _make_running(
    "RunningSum", SumMetric, "Sum over the last ``window`` updates (reference ``aggregation.py:673``)."
)
