"""Aggregation primitives: Max/Min/Sum/Cat/Mean + running variants.

Parity target: reference ``torchmetrics/aggregation.py`` (727 LoC) — the
primitive aggregators built directly on the state DSL. TPU-first notes:

- NaN handling (``nan_strategy``) runs eagerly in the shim ``update`` on
  concrete arrays; inside jit, use the functional kernels with masking instead
  (``ignore`` becomes a zero-weight mask, which is the static-shape form of the
  reference's boolean filtering).
- ``MeanMetric`` keeps (weighted-sum, weight-sum) — both plain ``sum`` states,
  so the distributed merge is a single fused psum.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat
from torchmetrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array

__all__ = [
    "BaseAggregator",
    "MaxMetric",
    "MinMetric",
    "SumMetric",
    "CatMetric",
    "MeanMetric",
    "RunningMean",
    "RunningSum",
]


class BaseAggregator(Metric):
    """Base class for aggregation metrics (reference ``aggregation.py:30-113``)."""

    is_differentiable = None
    higher_is_better = None
    full_state_update: bool = False

    def __init__(
        self,
        fn: Union[Callable, str],
        default_value: Union[Array, List],
        nan_strategy: Union[str, float] = "error",
        state_name: str = "value",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_nan_strategy = ("error", "warn", "ignore", "disable")
        if nan_strategy not in allowed_nan_strategy and not isinstance(nan_strategy, float):
            raise ValueError(
                f"Arg `nan_strategy` should either be a float or one of {allowed_nan_strategy}"
                f" but got {nan_strategy}."
            )
        self.nan_strategy = nan_strategy
        self.add_state(state_name, default=default_value, dist_reduce_fx=fn)
        self.state_name = state_name

    def _cast_and_nan_check_input(
        self, x: Union[float, Array], weight: Optional[Union[float, Array]] = None
    ) -> Tuple[Array, Array]:
        """Convert input to float arrays and apply the NaN strategy."""
        x = jnp.asarray(x, dtype=jnp.float32) if not hasattr(x, "dtype") else jnp.asarray(x).astype(jnp.float32)
        weight_was_scalar = weight is None or jnp.ndim(weight) == 0
        if weight is not None:
            weight = jnp.asarray(weight, dtype=jnp.float32)
        else:
            weight = jnp.ones_like(x)
        weight = jnp.broadcast_to(weight, x.shape)

        if self.nan_strategy == "disable":
            return x, weight
        nans = jnp.isnan(x) | jnp.isnan(weight)
        if bool(jnp.any(nans)):
            if self.nan_strategy == "error":
                raise RuntimeError("Encountered `nan` values in tensor")
            if self.nan_strategy in ("ignore", "warn"):
                if self.nan_strategy == "warn":
                    rank_zero_warn("Encountered `nan` values in tensor. Will be removed.", UserWarning)
                # eager path on concrete arrays: dynamic filtering is fine here
                keep = jnp.nonzero(~nans.reshape(-1))[0]
                x = x.reshape(-1)[keep]
                weight = weight.reshape(-1)[keep]
            else:
                x = jnp.where(nans, float(self.nan_strategy), x)
                if weight_was_scalar:
                    # reference parity quirk: it broadcasts the scalar weight
                    # BEFORE the nan check (aggregation.py:563), so its
                    # in-place `weight[nans] = value` writes the one underlying
                    # element through the 0-stride view and EVERY weight
                    # becomes the replacement value (nan_strategy=0.0 thus
                    # yields 0/0 = nan from MeanMetric)
                    weight = jnp.full_like(weight, float(self.nan_strategy))
                else:
                    weight = jnp.where(nans, float(self.nan_strategy), weight)
        return x, weight

    def update(self, value: Union[float, Array]) -> None:
        """Overwrite in child class."""

    def compute(self) -> Array:
        return getattr(self, self.state_name)


class MaxMetric(BaseAggregator):
    """Running maximum of a stream of values (reference ``aggregation.py:114``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.aggregation import MaxMetric
        >>> metric = MaxMetric()
        >>> metric.update(1.0)
        >>> metric.update(jnp.array([2.0, 3.0]))
        >>> metric.compute()
        Array(3., dtype=float32)
    """

    full_state_update = True

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("max", jnp.array(-jnp.inf, dtype=jnp.float32), nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        if value.size:
            self.value = jnp.maximum(self.value, jnp.max(value))


class MinMetric(BaseAggregator):
    """Running minimum of a stream of values (reference ``aggregation.py:219``)."""

    full_state_update = True

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("min", jnp.array(jnp.inf, dtype=jnp.float32), nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        if value.size:
            self.value = jnp.minimum(self.value, jnp.min(value))


class SumMetric(BaseAggregator):
    """Running sum of a stream of values (reference ``aggregation.py:324``)."""

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", jnp.array(0.0, dtype=jnp.float32), nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        if value.size:
            self.value = self.value + jnp.sum(value)


class CatMetric(BaseAggregator):
    """Concatenate a stream of values (reference ``aggregation.py:429``)."""

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("cat", [], nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        if value.size:
            self.value.append(value)

    def compute(self) -> Array:
        if isinstance(self.value, list) and self.value:
            return dim_zero_cat(self.value)
        return self.value


class MeanMetric(BaseAggregator):
    """Weighted running mean of a stream of values (reference ``aggregation.py:493``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.aggregation import MeanMetric
        >>> metric = MeanMetric()
        >>> metric.update(1.0)
        >>> metric.update(jnp.array([2.0, 3.0]))
        >>> metric.compute()
        Array(2., dtype=float32)
    """

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", jnp.array(0.0, dtype=jnp.float32), nan_strategy, **kwargs)
        self.add_state("weight", default=jnp.array(0.0, dtype=jnp.float32), dist_reduce_fx="sum")

    def update(self, value: Union[float, Array], weight: Union[float, Array] = 1.0) -> None:
        value, weight = self._cast_and_nan_check_input(value, weight)
        if value.size == 0:
            return
        self.value = self.value + jnp.sum(value * weight)
        self.weight = self.weight + jnp.sum(weight)

    def compute(self) -> Array:
        # raw division (reference aggregation.py:573): zero total weight —
        # e.g. the nan_strategy=0.0 broadcast-replacement quirk — yields nan
        return self.value / self.weight


def _make_running(name: str, base_cls: type, doc: str) -> type:
    from torchmetrics_tpu.wrappers.running import Running

    def __init__(self, window: int = 5, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        Running.__init__(self, base_cls(nan_strategy=nan_strategy, **kwargs), window=window)

    cls = type(name, (Running,), {"__init__": __init__, "__doc__": doc})
    cls.__module__ = __name__  # make the generated class picklable
    cls.__qualname__ = name
    return cls


RunningMean = _make_running(
    "RunningMean", MeanMetric, "Mean over the last ``window`` updates (reference ``aggregation.py:616``)."
)
RunningSum = _make_running(
    "RunningSum", SumMetric, "Sum over the last ``window`` updates (reference ``aggregation.py:673``)."
)
