"""Shared mean-aggregating base for audio metrics.

Every reference audio class keeps the same state pair (value sum + sample
count, e.g. ``audio/snr.py:88-89``); this base centralizes it.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from torchmetrics_tpu.metric import Metric

Array = jax.Array


class _AveragingAudioMetric(Metric):
    """Accumulates a per-sample metric as (sum, count) and computes the mean."""

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("measure_sum", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def _measure(self, preds: Array, target: Array) -> Array:
        raise NotImplementedError

    def update(self, preds: Array, target: Array) -> None:
        values = self._measure(preds, target)
        self.measure_sum = self.measure_sum + jnp.sum(values)
        self.total = self.total + values.size

    def compute(self) -> Array:
        return self.measure_sum / self.total
