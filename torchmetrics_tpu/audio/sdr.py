"""SDR metric classes (reference ``audio/sdr.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax

from torchmetrics_tpu.audio._base import _AveragingAudioMetric
from torchmetrics_tpu.functional.audio.sdr import signal_distortion_ratio
from torchmetrics_tpu.functional.audio.snr import (
    scale_invariant_signal_distortion_ratio,
    source_aggregated_signal_distortion_ratio,
)

Array = jax.Array


class SignalDistortionRatio(_AveragingAudioMetric):
    """Mean SDR in dB (distortion-filter formulation, device Toeplitz solve).

    Example:
        >>> import jax
        >>> from torchmetrics_tpu.audio import SignalDistortionRatio
        >>> preds = jax.random.normal(jax.random.PRNGKey(0), (8000,))
        >>> target = jax.random.normal(jax.random.PRNGKey(1), (8000,))
        >>> sdr = SignalDistortionRatio()
        >>> float(sdr(preds, target)) < 0
        True
    """

    def __init__(
        self,
        use_cg_iter: Optional[int] = None,
        filter_length: int = 512,
        zero_mean: bool = False,
        load_diag: Optional[float] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.use_cg_iter = use_cg_iter
        self.filter_length = filter_length
        self.zero_mean = zero_mean
        self.load_diag = load_diag

    def _measure(self, preds: Array, target: Array) -> Array:
        return signal_distortion_ratio(
            preds, target, self.use_cg_iter, self.filter_length, self.zero_mean, self.load_diag
        )


class ScaleInvariantSignalDistortionRatio(_AveragingAudioMetric):
    """Mean SI-SDR in dB.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.audio import ScaleInvariantSignalDistortionRatio
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> si_sdr = ScaleInvariantSignalDistortionRatio()
        >>> round(float(si_sdr(preds, target)), 4)
        18.403
    """

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(zero_mean, bool):
            raise ValueError(f"Expected argument `zero_mean` to be an bool, but got {zero_mean}")
        self.zero_mean = zero_mean

    def _measure(self, preds: Array, target: Array) -> Array:
        return scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=self.zero_mean)


class SourceAggregatedSignalDistortionRatio(_AveragingAudioMetric):
    """Mean SA-SDR over ``(..., spk, time)`` inputs."""

    def __init__(self, scale_invariant: bool = True, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(scale_invariant, bool):
            raise ValueError(f"Expected argument `scale_invariant` to be a bool, but got {scale_invariant}")
        if not isinstance(zero_mean, bool):
            raise ValueError(f"Expected argument `zero_mean` to be a bool, but got {zero_mean}")
        self.scale_invariant = scale_invariant
        self.zero_mean = zero_mean

    def _measure(self, preds: Array, target: Array) -> Array:
        return source_aggregated_signal_distortion_ratio(preds, target, self.scale_invariant, self.zero_mean)
