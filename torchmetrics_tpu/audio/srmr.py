"""SpeechReverberationModulationEnergyRatio (reference ``audio/srmr.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.audio._base import _AveragingAudioMetric
from torchmetrics_tpu.functional.audio.srmr import speech_reverberation_modulation_energy_ratio

Array = jax.Array


class SpeechReverberationModulationEnergyRatio(_AveragingAudioMetric):
    """Mean SRMR score over all processed waveforms.

    Self-contained JAX pipeline (gammatone + modulation filterbanks derived
    in-repo) — unlike the reference, no ``gammatone``/``torchaudio`` install
    is required.

    Example:
        >>> import jax
        >>> from torchmetrics_tpu.audio import SpeechReverberationModulationEnergyRatio
        >>> preds = jax.random.normal(jax.random.PRNGKey(1), (8000,))
        >>> metric = SpeechReverberationModulationEnergyRatio(8000)
        >>> metric.update(preds)
        >>> bool(metric.compute() > 0)
        True
    """

    is_differentiable = False

    def __init__(
        self,
        fs: int,
        n_cochlear_filters: int = 23,
        low_freq: float = 125,
        min_cf: float = 4,
        max_cf: Optional[float] = None,
        norm: bool = False,
        fast: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.fs = fs
        self.n_cochlear_filters = n_cochlear_filters
        self.low_freq = low_freq
        self.min_cf = min_cf
        self.max_cf = max_cf
        self.norm = norm
        self.fast = fast

    def update(self, preds: Array) -> None:  # type: ignore[override]
        values = speech_reverberation_modulation_energy_ratio(
            preds, self.fs, self.n_cochlear_filters, self.low_freq, self.min_cf, self.max_cf, self.norm, self.fast
        )
        self.measure_sum = self.measure_sum + jnp.sum(values)
        self.total = self.total + values.size

    def _measure(self, preds: Array, target: Array) -> Array:
        raise NotImplementedError
