"""SpeechReverberationModulationEnergyRatio (reference ``audio/srmr.py``)."""

from __future__ import annotations

from typing import Any

import jax

from torchmetrics_tpu.audio._base import _AveragingAudioMetric
from torchmetrics_tpu.functional.audio.srmr import speech_reverberation_modulation_energy_ratio
from torchmetrics_tpu.utilities.imports import _GAMMATONE_AVAILABLE

Array = jax.Array


class SpeechReverberationModulationEnergyRatio(_AveragingAudioMetric):
    """Mean SRMR score (requires the ``gammatone`` filterbank package).

    Raises:
        ModuleNotFoundError: if the ``gammatone`` package is not installed.
    """

    is_differentiable = False

    def __init__(
        self,
        fs: int,
        n_cochlear_filters: int = 23,
        low_freq: float = 125,
        min_cf: float = 4,
        max_cf: float = 128,
        norm: bool = False,
        fast: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not _GAMMATONE_AVAILABLE:
            raise ModuleNotFoundError(
                "SpeechReverberationModulationEnergyRatio metric requires that gammatone is installed."
                " Install as `pip install torchmetrics[audio]` or `pip install git+https://github.com/detly/gammatone`."
            )
        self.fs = fs
        self.n_cochlear_filters = n_cochlear_filters
        self.low_freq = low_freq
        self.min_cf = min_cf
        self.max_cf = max_cf
        self.norm = norm
        self.fast = fast

    def update(self, preds: Array) -> None:  # type: ignore[override]
        values = speech_reverberation_modulation_energy_ratio(
            preds, self.fs, self.n_cochlear_filters, self.low_freq, self.min_cf, self.max_cf, self.norm, self.fast
        )
        import jax.numpy as jnp

        self.measure_sum = self.measure_sum + jnp.sum(values)
        self.total = self.total + values.size

    def _measure(self, preds: Array, target: Array) -> Array:
        raise NotImplementedError
