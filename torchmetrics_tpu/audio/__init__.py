"""Modular audio metrics (reference ``torchmetrics/audio/__init__.py``)."""

from torchmetrics_tpu.audio.pit import PermutationInvariantTraining
from torchmetrics_tpu.audio.sdr import (
    ScaleInvariantSignalDistortionRatio,
    SignalDistortionRatio,
    SourceAggregatedSignalDistortionRatio,
)
from torchmetrics_tpu.audio.snr import (
    ComplexScaleInvariantSignalNoiseRatio,
    ScaleInvariantSignalNoiseRatio,
    SignalNoiseRatio,
)
from torchmetrics_tpu.audio.pesq import PerceptualEvaluationSpeechQuality
from torchmetrics_tpu.audio.srmr import SpeechReverberationModulationEnergyRatio
from torchmetrics_tpu.audio.stoi import ShortTimeObjectiveIntelligibility

__all__ = [
    "ComplexScaleInvariantSignalNoiseRatio",
    "PerceptualEvaluationSpeechQuality",
    "PermutationInvariantTraining",
    "ScaleInvariantSignalDistortionRatio",
    "ScaleInvariantSignalNoiseRatio",
    "ShortTimeObjectiveIntelligibility",
    "SignalDistortionRatio",
    "SignalNoiseRatio",
    "SourceAggregatedSignalDistortionRatio",
    "SpeechReverberationModulationEnergyRatio",
]
