"""ShortTimeObjectiveIntelligibility (reference ``audio/stoi.py``)."""

from __future__ import annotations

from typing import Any

import jax

from torchmetrics_tpu.audio._base import _AveragingAudioMetric
from torchmetrics_tpu.functional.audio.stoi import short_time_objective_intelligibility
from torchmetrics_tpu.utilities.imports import _PYSTOI_AVAILABLE

Array = jax.Array


class ShortTimeObjectiveIntelligibility(_AveragingAudioMetric):
    """Mean STOI score (host DSP via the ``pystoi`` package, like the reference).

    Raises:
        ModuleNotFoundError: if the ``pystoi`` package is not installed.
    """

    is_differentiable = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, fs: int, extended: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not _PYSTOI_AVAILABLE:
            raise ModuleNotFoundError(
                "STOI metric requires that `pystoi` is installed."
                " Either install as `pip install torchmetrics[audio]` or `pip install pystoi`."
            )
        self.fs = fs
        self.extended = extended

    def _measure(self, preds: Array, target: Array) -> Array:
        return short_time_objective_intelligibility(preds, target, self.fs, self.extended)
