"""SNR metric classes (reference ``audio/snr.py``)."""

from __future__ import annotations

from typing import Any

import jax

from torchmetrics_tpu.audio._base import _AveragingAudioMetric
from torchmetrics_tpu.functional.audio.snr import (
    complex_scale_invariant_signal_noise_ratio,
    scale_invariant_signal_noise_ratio,
    signal_noise_ratio,
)

Array = jax.Array


class SignalNoiseRatio(_AveragingAudioMetric):
    """Mean signal-to-noise ratio in dB.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.audio import SignalNoiseRatio
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> snr = SignalNoiseRatio()
        >>> round(float(snr(preds, target)), 4)
        16.1805
    """

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(zero_mean, bool):
            raise ValueError(f"Expected argument `zero_mean` to be an bool, but got {zero_mean}")
        self.zero_mean = zero_mean

    def _measure(self, preds: Array, target: Array) -> Array:
        return signal_noise_ratio(preds=preds, target=target, zero_mean=self.zero_mean)


class ScaleInvariantSignalNoiseRatio(_AveragingAudioMetric):
    """Mean scale-invariant signal-to-noise ratio in dB.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.audio import ScaleInvariantSignalNoiseRatio
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> si_snr = ScaleInvariantSignalNoiseRatio()
        >>> round(float(si_snr(preds, target)), 4)
        15.0918
    """

    def _measure(self, preds: Array, target: Array) -> Array:
        return scale_invariant_signal_noise_ratio(preds=preds, target=target)


class ComplexScaleInvariantSignalNoiseRatio(_AveragingAudioMetric):
    """Mean C-SI-SNR over complex spectra inputs ``(..., freq, time, 2)``."""

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(zero_mean, bool):
            raise ValueError(f"Expected argument `zero_mean` to be an bool, but got {zero_mean}")
        self.zero_mean = zero_mean

    def _measure(self, preds: Array, target: Array) -> Array:
        return complex_scale_invariant_signal_noise_ratio(preds=preds, target=target, zero_mean=self.zero_mean)
