"""PermutationInvariantTraining (reference ``audio/pit.py``)."""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from torchmetrics_tpu.audio._base import _AveragingAudioMetric
from torchmetrics_tpu.functional.audio.pit import permutation_invariant_training

Array = jax.Array


class PermutationInvariantTraining(_AveragingAudioMetric):
    """Mean best-permutation metric value over speaker assignments.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.audio import PermutationInvariantTraining
        >>> from torchmetrics_tpu.functional.audio import scale_invariant_signal_noise_ratio
        >>> preds = jnp.array([[[-0.0579,  0.3560, -0.9604], [-0.1719,  0.3205,  0.2951]]])
        >>> target = jnp.array([[[ 1.0958, -0.1648,  0.5228], [-0.4100,  1.1942, -0.5103]]])
        >>> pit = PermutationInvariantTraining(scale_invariant_signal_noise_ratio, mode="speaker-wise")
        >>> bool(pit(preds, target) < 0)
        False
    """

    def __init__(
        self,
        metric_func: Callable,
        mode: str = "speaker-wise",
        eval_func: str = "max",
        **kwargs: Any,
    ) -> None:
        base_kwargs = {
            key: kwargs.pop(key)
            for key in list(kwargs)
            if key in ("compute_on_cpu", "dist_sync_on_step", "process_group", "dist_sync_fn", "sync_on_compute",
                       "compute_with_cache", "distributed_available_fn", "auto_compile", "cat_state_capacity")
        }
        super().__init__(**base_kwargs)
        if eval_func not in ("max", "min"):
            raise ValueError(f'eval_func can only be "max" or "min" but got {eval_func}')
        if mode not in ("speaker-wise", "permutation-wise"):
            raise ValueError(f'mode can only be "speaker-wise" or "permutation-wise" but got {mode}')
        self.metric_func = metric_func
        self.mode = mode
        self.eval_func = eval_func
        self.metric_kwargs = kwargs  # remaining kwargs forwarded to metric_func

    def _measure(self, preds: Array, target: Array) -> Array:
        best_metric, _ = permutation_invariant_training(
            preds, target, self.metric_func, self.mode, self.eval_func, **self.metric_kwargs
        )
        return best_metric
