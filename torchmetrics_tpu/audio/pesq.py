"""PerceptualEvaluationSpeechQuality (reference ``audio/pesq.py``)."""

from __future__ import annotations

from typing import Any

import jax

from torchmetrics_tpu.audio._base import _AveragingAudioMetric
from torchmetrics_tpu.functional.audio.pesq import perceptual_evaluation_speech_quality
from torchmetrics_tpu.utilities.imports import _PESQ_AVAILABLE

Array = jax.Array


class PerceptualEvaluationSpeechQuality(_AveragingAudioMetric):
    """Mean PESQ score (host C DSP via the ``pesq`` package, like the reference).

    Raises:
        ModuleNotFoundError: if the ``pesq`` package is not installed.
    """

    is_differentiable = False
    plot_lower_bound: float = -0.5
    plot_upper_bound: float = 4.5

    def __init__(
        self,
        fs: int,
        mode: str,
        n_processes: int = 1,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not _PESQ_AVAILABLE:
            raise ModuleNotFoundError(
                "PerceptualEvaluationSpeechQuality metric requires that `pesq` is installed."
                " Either install as `pip install torchmetrics[audio]` or `pip install pesq`."
            )
        if fs not in (8000, 16000):
            raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
        if mode not in ("wb", "nb"):
            raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
        self.fs = fs
        self.mode = mode
        self.n_processes = n_processes

    def _measure(self, preds: Array, target: Array) -> Array:
        return perceptual_evaluation_speech_quality(preds, target, self.fs, self.mode, n_processes=self.n_processes)
