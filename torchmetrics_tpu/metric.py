"""Core metric runtime.

Parity target: reference ``torchmetrics/metric.py`` (1,211 LoC) — rebuilt around
an explicitly functional state model (SURVEY.md §7 design stance):

- A metric's state is a dict of immutable ``jax.Array`` leaves (or Python lists
  of arrays for append-mode "cat" states). ``update`` rebinds attributes; the
  numeric kernels live in ``torchmetrics_tpu.functional`` as pure jit-compiled
  functions.
- ``_reduce_states`` (cross-batch merge) and ``sync`` (cross-process merge) are
  the *same* reduction declared per-state via ``dist_reduce_fx`` — reference
  ``metric.py:195-272`` (add_state) and ``metric.py:393-425`` (_reduce_states).
- Distributed sync maps onto JAX collectives: eager multi-host gather
  (``utilities/distributed.py``) or in-jit ``lax.psum``/``all_gather`` via
  ``Metric.sync_in_jit`` / ``functional_state`` for use inside ``shard_map``.

There is no ``nn.Module`` here: device movement is ``jax.device_put``, dtype
policy is explicit, and autodiff flows through the functional kernels with
``jax.grad`` rather than a grad-enabled update context.
"""

from __future__ import annotations

import functools
import inspect
import itertools
import time
from abc import ABC, abstractmethod
from copy import deepcopy
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

# module scope (not inside `_fingerprint_exempt`): the exemption check sits on
# the eager per-update hot path, where a function-level import costs a dict
# lookup + lock round-trip per call; manifest.py imports nothing heavy
from torchmetrics_tpu._analysis.manifest import compiled_validation_eligible, fingerprint_skip_allowed
from torchmetrics_tpu._analysis.memsan import MEMSAN as _MEMSAN
from torchmetrics_tpu._analysis.memsan import check_metric as _memsan_check

# AOT executable-cache hot switch (_aot/state.py): consulted ONLY when a new
# executable is built (never per update call), so the unset-cache path stays
# instruction-identical to a build without the AOT machinery
from torchmetrics_tpu._aot.state import AOT as _AOT
from torchmetrics_tpu._aot.state import ensure_xla_cache as _ensure_xla_cache

# env-path arm of JAX's persistent compilation cache (layer 2): a no-op
# unless TM_TPU_AOT_CACHE was set before this process imported the runtime
_ensure_xla_cache()

# telemetry hot switch + light helpers (OBSERVABILITY.md). `_OBS.enabled` is
# the ONE check instrumented hot paths pay while telemetry is off: a slot
# attribute load + branch, no dict lookups, no allocation. Everything heavier
# lives behind it. state/events/telemetry import no jax/numpy at module
# scope; scopes pulls only jax symbol lookups (jax is already imported here).
from torchmetrics_tpu._observability import scopes as _obs_scopes
from torchmetrics_tpu._observability import tracing as _obs_trace
from torchmetrics_tpu._observability.events import BUS as _BUS
from torchmetrics_tpu._observability.profiling import LEDGER as _PROF_LEDGER
from torchmetrics_tpu._observability.state import OBS as _OBS
from torchmetrics_tpu._observability.telemetry import telemetry_for as _telemetry_for
from torchmetrics_tpu.utilities.data import (
    dim_zero_cat,
    dim_zero_max,
    dim_zero_mean,
    dim_zero_min,
    dim_zero_sum,
)
from torchmetrics_tpu.utilities.distributed import (
    distributed_available as _default_distributed_available,
    gather_all_tensors,
    sync_in_jit,
)
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError
from torchmetrics_tpu.utilities.prints import rank_zero_warn
from torchmetrics_tpu.utilities.ringbuffer import RingBuffer

Array = jax.Array

_STR_REDUCTIONS = {
    "sum": dim_zero_sum,
    "mean": dim_zero_mean,
    "max": dim_zero_max,
    "min": dim_zero_min,
    "cat": dim_zero_cat,
}

# "argument not passed" sentinel for partial policy updates
_UNSET = object()


def _is_array(x: Any) -> bool:
    return isinstance(x, (jnp.ndarray, np.ndarray)) or hasattr(x, "dtype") and hasattr(x, "shape")


def _squeeze_if_scalar(data: Any) -> Any:
    """Squeeze 1-element arrays to 0-d (reference ``utilities/data.py`` helper)."""
    if _is_array(data) and getattr(data, "size", None) == 1 and getattr(data, "ndim", 0) > 0:
        return jnp.squeeze(data)
    return data


def _flatten_maybe(seq: Sequence) -> list:
    out = []
    for el in seq:
        if isinstance(el, (list, tuple)):
            out.extend(el)
        else:
            out.append(el)
    return out


class Metric(ABC):
    """Base class for all metrics.

    Subclasses implement ``update(*args)`` (rebinding the states registered with
    :meth:`add_state`) and ``compute()``. The base class provides streaming
    ``forward``, cross-batch merging, distributed sync over JAX collectives,
    (de)serialization, cloning, and an operator algebra producing
    :class:`CompositionalMetric`.
    """

    __jit_unused_properties__: List[str] = ["is_differentiable"]
    is_differentiable: Optional[bool] = None
    higher_is_better: Optional[bool] = None
    full_state_update: Optional[bool] = None

    plot_lower_bound: Optional[float] = None
    plot_upper_bound: Optional[float] = None
    plot_legend_name: Optional[str] = None

    def __init__(self, **kwargs: Any) -> None:
        # config kwargs (reference metric.py:100-148), each type-validated
        self.compute_on_cpu = kwargs.pop("compute_on_cpu", False)
        if not isinstance(self.compute_on_cpu, bool):
            raise ValueError(f"Expected keyword argument `compute_on_cpu` to be a `bool` but got {self.compute_on_cpu}")
        self.dist_sync_on_step = kwargs.pop("dist_sync_on_step", False)
        if not isinstance(self.dist_sync_on_step, bool):
            raise ValueError(
                f"Expected keyword argument `dist_sync_on_step` to be a `bool` but got {self.dist_sync_on_step}"
            )
        # process subsets: a sequence of process indices (eager multi-host
        # gather filters to members) — the mesh-axis-subset analogue of the
        # reference's torch.distributed group handle (``metric.py:125``); for
        # in-jit sync use `sync_in_jit(..., axis_index_groups=...)` instead
        self.process_group = kwargs.pop("process_group", None)
        if self.process_group is not None and not (
            isinstance(self.process_group, (list, tuple))
            and all(isinstance(i, int) for i in self.process_group)
            and len(set(self.process_group)) == len(self.process_group)
        ):
            raise ValueError(
                "Expected keyword argument `process_group` to be `None` or a list/tuple of unique"
                f" process indices but got {self.process_group}"
            )
        self.dist_sync_fn = kwargs.pop("dist_sync_fn", None)
        if self.dist_sync_fn is not None and not callable(self.dist_sync_fn):
            raise ValueError(
                f"Expected keyword argument `dist_sync_fn` to be a callable function but got {self.dist_sync_fn}"
            )
        self.distributed_available_fn = kwargs.pop("distributed_available_fn", None) or _default_distributed_available
        self.sync_on_compute = kwargs.pop("sync_on_compute", True)
        if not isinstance(self.sync_on_compute, bool):
            raise ValueError(
                f"Expected keyword argument `sync_on_compute` to be a `bool` but got {self.sync_on_compute}"
            )
        self.compute_with_cache = kwargs.pop("compute_with_cache", True)
        if not isinstance(self.compute_with_cache, bool):
            raise ValueError(
                f"Expected keyword argument `compute_with_cache` to be a `bool` but got {self.compute_with_cache}"
            )
        # TPU-native extension: transparently route repeat-shape `update()` /
        # `forward()` calls through the shape-keyed compiled path. The first
        # call with any argument signature always runs eagerly (running
        # value-dependent validation and warming lazily-shaped states); repeat
        # signatures replay one XLA executable. Metrics constructed with
        # `validate_args=True` never auto-compile (their per-batch value checks
        # must keep running), and any metric whose update cannot trace is
        # permanently dropped back to the eager path on first failure.
        self.auto_compile = kwargs.pop("auto_compile", True)
        if not isinstance(self.auto_compile, bool):
            raise ValueError(f"Expected keyword argument `auto_compile` to be a `bool` but got {self.auto_compile}")
        # TPU-native extension (SURVEY §5/§7): bound append-mode ("cat") states
        # to a fixed-capacity device ring buffer instead of an unbounded list
        self.cat_state_capacity = kwargs.pop("cat_state_capacity", None)
        if self.cat_state_capacity is not None and not (
            isinstance(self.cat_state_capacity, int) and self.cat_state_capacity > 0
        ):
            raise ValueError(
                "Expected keyword argument `cat_state_capacity` to be `None` or a positive integer"
                f" but got {self.cat_state_capacity}"
            )
        # resilience knobs (torchmetrics_tpu/_resilience, RESILIENCE.md):
        # `sync_policy` opts the eager multi-host sync into the guarded path
        # (handshake + timeout/retry/backoff + graceful degradation);
        # `nan_policy` arms the NaN/Inf state sentinel after every eager
        # update. An EXPLICIT `sync_policy=None` opts out of the process-wide
        # default policy; omitting the kwarg inherits it.
        self._sync_policy_explicit = "sync_policy" in kwargs
        self.sync_policy = kwargs.pop("sync_policy", None)
        self.nan_policy = kwargs.pop("nan_policy", None)
        self._validate_resilience_knobs()
        self._resilience_events: List[Any] = []
        self._quarantined_updates: int = 0
        # update-journal hook: a SnapshotManager (RESILIENCE.md "Snapshots")
        # binds itself here; every completed update/forward then journals
        # its batch arguments for preemption-safe restore+replay. None (the
        # default) costs one dict probe per update on the hot path.
        self._snapshot_hook: Optional[Any] = None
        if kwargs:
            kwargs_ = [f"`{a}`" for a in sorted(kwargs)]
            raise ValueError(f"Unexpected keyword arguments: {', '.join(kwargs_)}")

        self._update_signature = inspect.signature(self.update)
        self.update: Callable = self._wrap_update(self.update)  # type: ignore[method-assign]
        self.compute: Callable = self._wrap_compute(self.compute)  # type: ignore[method-assign]
        self._computed: Any = None
        self._forward_cache: Any = None
        self._update_count: int = 0
        self._to_sync = self.sync_on_compute
        self._should_unsync = True

        self._defaults: Dict[str, Union[Array, List]] = {}
        self._persistent: Dict[str, bool] = {}
        self._reductions: Dict[str, Union[str, Callable, None]] = {}

        self._is_synced = False
        self._cache: Optional[Dict[str, Union[Array, List]]] = None
        self._dtype_policy: Optional[Any] = None

        # auto-compile bookkeeping: seen argument signatures, cached state
        # names, and per-path disable flags (flipped on first trace failure)
        self._auto_sigs: Dict[Any, int] = {}
        self._auto_fwd_sigs: Dict[Any, int] = {}
        self._auto_names: Optional[List[str]] = None
        self._auto_disabled = False
        self._auto_forward_disabled = False
        # compiled-validation bookkeeping: when `validate_args=True` and the
        # metric provides `_traced_value_flags`, the per-batch value checks run
        # fused inside the compiled update and OR-accumulate device-side here;
        # violations surface at the next host synchronization point
        self._viol_msgs: Optional[Tuple[str, ...]] = None
        self._viol_sevs: Optional[Tuple[str, ...]] = None
        self._viol_flags: Optional[Array] = None
        self._traced_validation_supported: Optional[bool] = None

    # ------------------------------------------------------------------ state
    @property
    def _update_called(self) -> bool:
        return self._update_count > 0

    @property
    def update_called(self) -> bool:
        """True if ``update``/``forward`` has been called since construction/reset."""
        return self._update_called

    @property
    def update_count(self) -> int:
        return self._update_count

    @property
    def metric_state(self) -> Dict[str, Union[List[Array], Array]]:
        """Current value of all registered states."""
        return {attr: getattr(self, attr) for attr in self._defaults}

    def add_state(
        self,
        name: str,
        default: Union[Array, List],
        dist_reduce_fx: Optional[Union[str, Callable]] = None,
        persistent: bool = False,
    ) -> None:
        """Register a metric state (reference ``metric.py:195-272``).

        ``default`` is a ``jax.Array`` (accumulator mode) or an empty list
        (append/"cat" mode). ``dist_reduce_fx`` declares the merge semantics
        used by both cross-batch accumulation and distributed sync:
        ``"sum" | "mean" | "max" | "min" | "cat" | None | callable``.
        """
        if not name.isidentifier():
            raise ValueError(f"Argument `name` must be a valid python attribute name, but got {name}")
        is_list = isinstance(default, list)
        is_ring = isinstance(default, RingBuffer)
        if not (_is_array(default) or (is_list and len(default) == 0) or is_ring):
            raise ValueError("state variable must be a jax array or any empty list (where you can append arrays)")
        if dist_reduce_fx is not None and not (dist_reduce_fx in _STR_REDUCTIONS or callable(dist_reduce_fx)):
            raise ValueError(
                "`dist_reduce_fx` must be callable or one of ['mean', 'sum', 'cat', 'min', 'max', None]"
            )
        if is_ring:
            if dist_reduce_fx != "cat":
                raise ValueError(
                    f"RingBuffer states require `dist_reduce_fx='cat'`, but state {name!r} declared"
                    f" {dist_reduce_fx!r}"
                )
            if len(default):
                raise ValueError(f"RingBuffer default for state {name!r} must be empty")
        if is_list and self.cat_state_capacity is not None and dist_reduce_fx in ("cat", None):
            default = RingBuffer(self.cat_state_capacity)
            is_list, is_ring = False, True
        if is_ring:
            setattr(self, name, default.copy())
            self._defaults[name] = default.copy_empty()
        else:
            if not is_list:
                default = jnp.asarray(default)
            setattr(self, name, list(default) if is_list else default)
            self._defaults[name] = list(default) if is_list else default
        self._persistent[name] = persistent
        self._reductions[name] = dist_reduce_fx
        # registering a state changes the cross-process structure contract:
        # the next guarded sync must re-run the handshake
        self.__dict__.pop("_handshake_ok_digest", None)

    # --------------------------------------------------------------- forward
    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Update global state AND return the metric on just this batch.

        Reference dual-mode (``metric.py:275-306``): metrics with
        ``full_state_update=False`` use the efficient single-update path where
        the batch state is merged into the global state via the declared
        reductions; otherwise the conservative double-update path runs.
        """
        if self._is_synced:
            raise TorchMetricsUserError(
                "The Metric shouldn't be synced when performing ``forward``. "
                "HINT: Did you forget to call ``unsync``?"
            )
        # the stash/reset/update/compute/merge dance runs update() on
        # batch-local state: suspend the snapshot journal for its duration
        # and record the batch ONCE below, when the global state is final
        suspended = "_journal_suspend" in self.__dict__
        if not suspended:
            self.__dict__["_journal_suspend"] = True
        # the forward span parents the dance's inner update/compute spans,
        # so one forward call still reads as ONE causally-ordered request
        _sp = _obs_trace.begin_span("forward", type(self).__name__) if _OBS.tracing else None
        _sp_err: Optional[BaseException] = None
        try:
            if self.full_state_update or self.full_state_update is None or self.dist_sync_on_step:
                self._forward_cache = self._forward_full_state_update(*args, **kwargs)
            else:
                handled, batch_val = self._try_auto_forward(args, kwargs)
                self._forward_cache = batch_val if handled else self._forward_reduce_state_update(*args, **kwargs)
        except BaseException as err:
            _sp_err = err
            raise
        finally:
            if _sp is not None:
                _obs_trace.end_span(_sp, _sp_err)
            if not suspended:
                self.__dict__.pop("_journal_suspend", None)
        # replay re-runs forward entries through plain update(): the state
        # transition is identical, only the (recomputed-anyway) batch value
        # differs — so the journal tags them "update"
        self._journal_record("update", args, kwargs)
        return self._forward_cache

    def _forward_full_state_update(self, *args: Any, **kwargs: Any) -> Any:
        """Double-update path (reference ``metric.py:308-351``)."""
        self.update(*args, **kwargs)
        if self.nan_policy == "quarantine" and self.__dict__.get("_nan_last_quarantined"):
            # the NaN sentinel dropped this batch from the global state;
            # skip the batch-value replay entirely — it would re-update (and
            # re-record the quarantine) and then compute on an empty state
            return None
        self._to_sync = self.dist_sync_on_step

        cache = self._copy_state_dict()
        update_count = self._update_count
        try:
            self.reset()
            # the batch-only replay must not advance the NaN-sentinel stream
            # ordinal a second time (the first update above already did)
            self.__dict__["_nan_replay"] = True
            try:
                self.update(*args, **kwargs)
            finally:
                self.__dict__.pop("_nan_replay", None)
            batch_val = self.compute()
        except Exception:
            # reset() may surface a pending deferred violation (it clears,
            # resets, THEN raises), and the batch replay may fail validation:
            # either way the accumulated state lives only in the local above
            # and must be restored before propagating
            self._update_count = update_count
            self._restore_state(cache)
            self._computed = None
            self._is_synced = False
            self._to_sync = self.sync_on_compute
            raise

        # restore global state
        self._update_count = update_count
        self._restore_state(cache)
        self._computed = None
        self._is_synced = False
        self._should_unsync = True
        self._to_sync = self.sync_on_compute
        return batch_val

    def _forward_reduce_state_update(self, *args: Any, **kwargs: Any) -> Any:
        """Single-update path (reference ``metric.py:353-391``)."""
        global_state = self._copy_state_dict()
        update_count = self._update_count
        try:
            self.reset()
        except Exception:
            # reset() surfaces pending deferred violations AFTER resetting:
            # restore the accumulation (stashed only in the local above)
            # before propagating
            self._update_count = update_count
            self._restore_state(global_state)
            raise

        self._to_sync = self.dist_sync_on_step
        self._should_unsync = False

        try:
            self.update(*args, **kwargs)
            quarantined = self.nan_policy == "quarantine" and self.__dict__.get("_nan_last_quarantined")
            # a quarantined batch's state was rolled back to reset-empty:
            # computing on it would crash cat-state metrics ("no samples to
            # concatenate"), so the dropped batch yields no batch value
            batch_val = None if quarantined else self.compute()
        except Exception:
            # the batch failed validation (or the NaN sentinel raised): the
            # accumulated global state lives only in the local above, so it
            # must be restored before propagating — otherwise one bad batch
            # destroys the whole accumulation
            self._update_count = update_count
            self._restore_state(global_state)
            self._should_unsync = True
            self._to_sync = self.sync_on_compute
            self._computed = None
            self._is_synced = False
            raise

        if quarantined:
            # restore the global state untouched: merging the rolled-back
            # defaults would contaminate mean-reduced states
            self._update_count = update_count
            self._restore_state(global_state)
        else:
            self._update_count = update_count + 1
            self._reduce_states(global_state)

        self._should_unsync = True
        self._to_sync = self.sync_on_compute
        self._computed = None
        self._is_synced = False
        return batch_val

    def _reduce_states(
        self,
        incoming_state: Dict[str, Any],
        incoming_weight: Optional[float] = None,
        local_weight: float = 1.0,
    ) -> None:
        """Merge ``incoming_state`` into the current state per-reduction.

        Reference ``metric.py:393-425``. For ``mean`` states the merge is a
        weighted average: in the forward path the incoming (previous global)
        state carries ``n-1`` updates and the local batch one, reproducing the
        reference's running-mean formula; ``merge_state`` passes explicit
        update counts so multi-update merges stay correctly weighted.
        """
        for attr in self._defaults:
            local_state = getattr(self, attr)
            global_state = incoming_state[attr]
            reduce_fn = self._reductions[attr]
            if reduce_fn == "sum":
                reduced = global_state + local_state
            elif reduce_fn == "mean":
                gw = float(self._update_count - local_weight) if incoming_weight is None else float(incoming_weight)
                lw = float(local_weight)
                reduced = (gw * global_state + lw * local_state) / (gw + lw)
            elif reduce_fn == "max":
                reduced = jnp.maximum(global_state, local_state)
            elif reduce_fn == "min":
                reduced = jnp.minimum(global_state, local_state)
            elif reduce_fn in ("cat", None) and isinstance(global_state, RingBuffer):
                reduced = global_state.copy().extend(local_state)
            elif (reduce_fn == "cat" or reduce_fn is None) and isinstance(global_state, list):
                reduced = global_state + list(local_state)
            elif reduce_fn is None and _is_array(global_state):
                default = self._defaults.get(attr)

                def _stacked(v: Any) -> bool:
                    # a (k, *default_shape) collection produced by earlier
                    # merges, as opposed to a plain state value
                    return (
                        _is_array(default)
                        and getattr(v, "ndim", 0) == getattr(default, "ndim", 0) + 1
                        and tuple(v.shape[1:]) == tuple(default.shape)
                    )

                if _stacked(global_state) or _stacked(local_state):
                    # chained/tree merges: either side may already be stacked
                    # (N-replica merge_state chains, pairwise shard reduces);
                    # normalize both to (k, ...) and concatenate
                    g = global_state if _stacked(global_state) else global_state[None]
                    loc = local_state if _stacked(local_state) else local_state[None]
                    reduced = jnp.concatenate([g, loc])
                else:
                    reduced = jnp.stack([global_state, local_state])
            elif reduce_fn == "cat" and _is_array(global_state):
                reduced = jnp.concatenate([jnp.atleast_1d(global_state), jnp.atleast_1d(local_state)])
            elif callable(reduce_fn):
                reduced = reduce_fn(jnp.stack([global_state, local_state]))
            else:
                raise TorchMetricsUserError(f"Cannot reduce state {attr} with reduction {reduce_fn}")
            setattr(self, attr, reduced)

    # ---------------------------------------------------------------- update
    def _wrap_update(self, update: Callable) -> Callable:
        @functools.wraps(update)
        def wrapped_func(*args: Any, **kwargs: Any) -> None:
            # request tracing rides its own slot-bool (`_OBS.tracing`): off,
            # this seam pays one branch and a None store; on, the span links
            # into the ambient trace_context tree via the contextvar
            _sp = _obs_trace.begin_span("update", type(self).__name__) if _OBS.tracing else None
            _sp_err: Optional[BaseException] = None
            try:
                return self._update_impl(update, _sp, args, kwargs)
            except BaseException as err:
                _sp_err = err
                raise
            finally:
                if _sp is not None:
                    _obs_trace.end_span(_sp, _sp_err)

        wrapped_func.__wrapped_by_metric__ = True  # type: ignore[attr-defined]
        return wrapped_func

    def _update_impl(self, update: Callable, _sp: Any, args: tuple, kwargs: Dict[str, Any]) -> None:
        """The body of every wrapped ``update`` (``_sp`` = the seam's open span or None)."""
        if self._try_auto_update(args, kwargs):
            if _sp is not None:
                _sp.attrs["path"] = "auto"
            self._journal_record("update", args, kwargs)
            return None
        if _sp is not None:
            _sp.attrs["path"] = "eager"
        self._check_pending_violations()
        self._computed = None
        self._update_count += 1
        # only pay the fingerprint where a compiled path could engage AND
        # the static analyzer hasn't already proven the whole class chain
        # free of unregistered-attribute mutation (R1 certification —
        # see torchmetrics_tpu/_analysis and ANALYSIS.md)
        eligible = self._auto_eligible()
        guard = eligible and not self._fingerprint_exempt()
        if _OBS.enabled:
            _t = _telemetry_for(self)
            _t.inc("fingerprint|outcome=check" if guard else "fingerprint|outcome=skip" if eligible else "fingerprint|outcome=ineligible")
        if guard:
            # the keep-alive list pins every fingerprinted object for the
            # duration of the update, so a freed-and-reallocated object
            # cannot alias a stale id in the comparison
            before, _keepalive = self._host_attr_snapshot()
        # quarantine is the only nan_policy needing a rollback point; the
        # pre-update list lengths let the sentinel scan only the elements
        # THIS batch appended (cat-state streams stay O(batch), not O(n))
        pre_state = pre_lens = None
        if self.nan_policy is not None:
            # stream-position ordinal for sentinel telemetry: forward()'s
            # stash/reset dance makes `_update_count` batch-local, so the
            # recorded "which batch was dropped" needs its own counter
            # (the full-state forward's batch-only replay doesn't count)
            if not self.__dict__.get("_nan_replay"):
                self.__dict__["_nan_seen_batches"] = self.__dict__.get("_nan_seen_batches", 0) + 1
            pre_lens = {}
            for n in self._defaults:
                v = getattr(self, n)
                if isinstance(v, list):
                    pre_lens[n] = len(v)
            if self.nan_policy == "quarantine":
                pre_state = self._quarantine_snapshot()
                self.__dict__["_nan_last_quarantined"] = False
        if _OBS.enabled:
            self._obs_call("update_calls|path=eager", "update_eager", "update", lambda: update(*args, **kwargs))
        else:
            update(*args, **kwargs)
        if guard and self._host_attr_snapshot()[0] != before:
            # update() mutates plain (unregistered) python attributes; a
            # traced replay would silently freeze those side effects, so
            # the compiled paths are permanently off for this instance
            self._auto_disabled = True
            self._auto_forward_disabled = True
            if _OBS.enabled:
                self._obs_auto_disabled("update mutated unregistered host attributes")
        if self.nan_policy is not None:
            self._guard_nonfinite_states(pre_state, pre_lens)
        if self._dtype_policy is not None:
            self._apply_dtype_policy()
        if self.compute_on_cpu:
            self._move_list_states_to_cpu()
        self._journal_record("update", args, kwargs)
        return None

    def _journal_record(self, method: str, args: tuple, kwargs: Dict[str, Any]) -> None:
        """Feed one *completed* state transition to the attached SnapshotManager.

        Runs only after the update committed (and after quarantine rollback,
        dtype policy, and CPU offload), so the journal never records a batch
        whose effects are not durably represented by replaying it. Inner
        updates of the forward stash/reset dance are suppressed via
        ``_journal_suspend`` — mid-dance state is batch-local and must not
        be journaled or snapshotted.
        """
        if method == "update" and _MEMSAN.enabled:
            # every update path (eager/auto/jit/forward) commits through this
            # seam, so one sanitizer site cross-checks them all; disabled
            # cost is one slot load + branch (memsan_disabled_retention)
            _memsan_check(self)
        hook = self.__dict__.get("_snapshot_hook")
        if hook is not None and "_journal_suspend" not in self.__dict__:
            hook.record(self, method, args, kwargs)

    # ------------------------------------------------------------- telemetry
    # Helpers below only ever run with telemetry ENABLED (callers guard on
    # `_OBS.enabled`); they may allocate, probe dicts, and read the clock.
    # All mutation is host-side at eager boundaries — never under trace.

    # _obs_call ops the cost ledger accounts (jit/scan compiled dispatches);
    # eager ops stay out — profiling prices device executables, not host loops
    _PROF_OPS = frozenset({"update_jit", "update_scan"})

    def _obs_call(self, counter_key: Optional[str], op: str, method: str, fn: Callable) -> Any:
        """Run ``fn`` counted, latency-sampled, and profiler-annotated."""
        telem = _telemetry_for(self)
        if counter_key:
            telem.inc(counter_key)
        sample = telem.sample_due(op)
        prof = _OBS.profiling and op in self._PROF_OPS
        t0 = time.perf_counter() if (sample or prof) else 0.0
        if _OBS.profile_scopes:
            with _obs_scopes.annotation(f"{type(self).__name__}.{method}"):
                out = fn()
        else:
            out = fn()
        if sample or prof:
            elapsed = time.perf_counter() - t0
            if prof:
                _PROF_LEDGER.record_step(op, type(self).__name__, elapsed)
            if sample:
                telem.observe(op, elapsed)
        return out

    def _obs_compile_event(
        self, kind: str, treedef: Any, statics: Any, shapes_dtypes: Any, built: bool = True
    ) -> None:
        """Report one compiled-path cache key for recompile-churn tracking.

        Deduplicated on the HASHABLE signature before any string building, so
        steady-state repeat-signature callers (``jit_update``/``scan_update``
        report per call) pay one set probe, not four ``repr()``s.
        """
        seen = self.__dict__.setdefault("_obs_seen_sigs", set())
        sig_key = (kind, treedef, statics, shapes_dtypes, self._dtype_policy is not None and str(self._dtype_policy))
        if sig_key in seen:
            return
        if len(seen) < 512:  # churn streams must not grow host memory unboundedly
            seen.add(sig_key)
        policy = "none" if self._dtype_policy is None else str(jnp.dtype(self._dtype_policy).name)
        _telemetry_for(self).compile_event(
            kind,
            {
                "arg_structure": str(treedef),
                "static_args": repr(statics),
                "shapes": repr(tuple(s for s, _ in shapes_dtypes)),
                "dtypes": repr(tuple(d for _, d in shapes_dtypes)),
                "dtype_policy": policy,
            },
            built=built,
        )

    def _obs_auto_disabled(self, reason: str) -> None:
        """Record why the transparent compiled path switched off (event bus)."""
        _telemetry_for(self).inc("auto_path_disabled")
        _BUS.publish("auto_path_disabled", type(self).__name__, reason)

    def telemetry_report(self) -> Any:
        """Runtime telemetry snapshot for this metric (OBSERVABILITY.md).

        Returns a :class:`~torchmetrics_tpu._observability.telemetry.TelemetryReport`
        with per-path update counters, fingerprint/quarantine/deferred-violation
        counts, compile + recompile-churn statistics, sync attempts, and
        sampled latency reservoirs. With telemetry disabled (the default) the
        report is empty with ``enabled=False`` — enable collection with
        ``TM_TPU_TELEMETRY=1`` or
        :func:`torchmetrics_tpu._observability.set_telemetry_enabled`.
        """
        from torchmetrics_tpu._observability.telemetry import report_for

        return report_for(self)

    def _fingerprint_exempt(self) -> bool:
        """True when the R1-certified manifest covers this instance's class.

        The trace-safety analyzer (``tools/lint_metrics.py --write-manifest``)
        records every class whose static MRO provably never mutates an
        unregistered attribute; for those the per-``update()``
        ``_host_attr_snapshot`` fingerprint is redundant work. Any class the
        analyzer has not seen (user subclasses included) keeps the guard.
        """
        # per-class memoization lives in the manifest module, so the runtime
        # toggle (set_fingerprint_skip_enabled) invalidates in one place
        return fingerprint_skip_allowed(type(self))

    def _host_attr_snapshot(self) -> Tuple[List[tuple], List[Any]]:
        """Fingerprint of plain (non-state, non-private) host attributes.

        Auto-compile replays ``update()`` as a traced executable, which would
        silently freeze host-side mutations of unregistered attributes (a
        python counter, a list kept outside ``add_state``). Every eager pass
        fingerprints those attributes; any observed change disables the
        compiled paths for this instance. Private (``_``-prefixed) attributes
        are the metric machinery's own bookkeeping and are not guarded.

        Returns ``(fingerprint, keepalive)``: the caller must hold the
        keep-alive list across the update so identity-fingerprinted objects
        cannot be freed and reallocated at the same address mid-comparison.
        """
        keepalive: List[Any] = []

        def fp(v: Any):
            # one-level value fingerprint; arrays/objects degrade to identity.
            # Mutations nested deeper than one container level (or occurring
            # only on inputs never seen eagerly) are out of the guard's reach.
            if isinstance(v, (bool, int, float, complex, str, bytes, type(None))):
                return v
            keepalive.append(v)
            return id(v)

        snap: List[tuple] = []
        for k, v in self.__dict__.items():
            if k.startswith("_") or k in self._defaults:
                continue
            if callable(v):
                continue
            if _is_array(v) or isinstance(v, RingBuffer):
                # unregistered array attrs are identity-fingerprinted:
                # `self.cache = preds` reassigns (new id) and must disable
                # the compiled paths just like a mutated python container
                keepalive.append(v)
                snap.append((k, id(v)))
            elif isinstance(v, (bool, int, float, complex, str, bytes, type(None))):
                snap.append((k, v))
            elif isinstance(v, dict) and len(v) <= 16:
                snap.append((k, id(v), tuple((fp(dk), fp(dv)) for dk, dv in v.items())))
            elif isinstance(v, (list, tuple)) and len(v) <= 16:
                snap.append((k, id(v), tuple(fp(i) for i in v)))
            elif isinstance(v, (list, tuple)):
                # >16 entries: (id, len) alone misses same-length in-place
                # mutation (ADVICE r5), so fold in a spread sample of elements
                # — O(1) indexing keeps huge lists cheap to fingerprint
                n = len(v)
                idxs = sorted({0, 1, 2, n // 4, n // 2, (3 * n) // 4, n - 3, n - 2, n - 1})
                snap.append((k, id(v), n, tuple((i, fp(v[i])) for i in idxs)))
            elif isinstance(v, (dict, set)):
                # unindexable containers: sample the first 8 entries (insertion
                # order for dicts, hash order for sets — both stable while the
                # container is unmutated). Mutations confined to unsampled
                # entries remain out of the guard's reach; see docstring.
                if isinstance(v, dict):
                    sample = tuple((fp(dk), fp(dv)) for dk, dv in itertools.islice(v.items(), 8))
                else:
                    sample = tuple(fp(i) for i in itertools.islice(v, 8))
                snap.append((k, id(v), len(v), sample))
            else:
                keepalive.append(v)
                snap.append((k, id(v)))
        return snap, keepalive

    def _apply_dtype_policy(self) -> None:
        """Re-cast floating states to the ``set_dtype`` policy after an update.

        torch's in-place ``state += batch`` keeps a half-precision buffer
        half; functional rebinding promotes, so the declared dtype is
        re-applied — to plain arrays, appended list chunks, and ring-buffer
        storage alike (mirroring what ``set_dtype`` itself casts).
        """
        dst = self._dtype_policy
        for attr in self._defaults:
            current = getattr(self, attr)
            if isinstance(current, RingBuffer):
                if current.data is not None and jnp.issubdtype(current.data.dtype, jnp.floating):
                    current.data = current.data.astype(dst)
            elif isinstance(current, list):
                object.__setattr__(
                    self,
                    attr,
                    [
                        v.astype(dst) if _is_array(v) and jnp.issubdtype(v.dtype, jnp.floating) else v
                        for v in current
                    ],
                )
            elif _is_array(current) and jnp.issubdtype(current.dtype, jnp.floating):
                object.__setattr__(self, attr, current.astype(dst))

    def _move_list_states_to_cpu(self) -> None:
        """Offload append-mode (list) states to host memory after each update.

        The HBM-relief analogue of reference ``metric.py:483-488``: cat states
        grow unboundedly, so each appended chunk is committed to the CPU
        backend via ``device_put``. Compute then runs on the CPU arrays (JAX
        executes ops where their operands are committed).
        """
        cpu = jax.devices("cpu")[0]
        for attr in self._defaults:
            value = getattr(self, attr)
            if isinstance(value, RingBuffer):
                value.to_device(cpu)
            elif isinstance(value, list):
                setattr(self, attr, [jax.device_put(v, cpu) for v in value])

    def _wrap_compute(self, compute: Callable) -> Callable:
        @functools.wraps(compute)
        def wrapped_func(*args: Any, **kwargs: Any) -> Any:
            _sp = _obs_trace.begin_span("compute", type(self).__name__) if _OBS.tracing else None
            _sp_err: Optional[BaseException] = None
            try:
                return _compute_impl(_sp, args, kwargs)
            except BaseException as err:
                _sp_err = err
                raise
            finally:
                if _sp is not None:
                    _obs_trace.end_span(_sp, _sp_err)

        def _compute_impl(_sp: Any, args: tuple, kwargs: Dict[str, Any]) -> Any:
            self._check_pending_violations()
            if not self.update_called:
                rank_zero_warn(
                    f"The ``compute`` method of metric {self.__class__.__name__}"
                    " was called before the ``update`` method which may lead to errors,"
                    " as metric states have not yet been updated.",
                    UserWarning,
                )
            if self._computed is not None:
                if _sp is not None:
                    _sp.attrs["outcome"] = "cache_hit"
                if _OBS.enabled:
                    _telemetry_for(self).inc("compute_calls|outcome=cache_hit")
                return self._computed
            # the sync() inside sync_context opens its own child span, so a
            # traced compute reads update -> sync -> compute causally
            with self.sync_context(
                dist_sync_fn=self.dist_sync_fn,
                should_sync=self._to_sync,
                should_unsync=self._should_unsync,
            ):
                if _OBS.enabled:
                    value = _squeeze_if_scalar(
                        self._obs_call(
                            "compute_calls|outcome=computed", "compute", "compute",
                            lambda: compute(*args, **kwargs),
                        )
                    )
                else:
                    value = _squeeze_if_scalar(compute(*args, **kwargs))
            if self.compute_with_cache:
                self._computed = value
            return value

        wrapped_func.__wrapped_by_metric__ = True  # type: ignore[attr-defined]
        return wrapped_func

    @abstractmethod
    def update(self, *_: Any, **__: Any) -> None:
        """Override: accumulate batch statistics into the registered states."""

    @abstractmethod
    def compute(self) -> Any:
        """Override: compute the final value from the current state."""

    # ----------------------------------------------------------------- sync
    def sync(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        distributed_available: Optional[Callable] = None,
    ) -> None:
        """Gather + reduce state across processes (reference ``metric.py:490-532``).

        With a :class:`~torchmetrics_tpu._resilience.policy.SyncPolicy`
        attached (per-metric ``sync_policy`` or the process-wide default),
        the gather runs guarded: structure handshake, per-attempt timeout,
        retry with backoff, and — on exhaustion — graceful degradation to
        local-only state with a recorded ``DegradationEvent`` instead of a
        deadlock or an exception mid-eval. Without a policy the legacy
        unguarded path runs unchanged.
        """
        if self._is_synced and should_sync:
            raise TorchMetricsUserError("The Metric has already been synced.")
        if distributed_available is None and self.distributed_available_fn is not None:
            distributed_available = self.distributed_available_fn
        is_distributed = distributed_available() if callable(distributed_available) else None
        if not should_sync or not is_distributed:
            return
        if dist_sync_fn is None:
            dist_sync_fn = self.dist_sync_fn or gather_all_tensors
        self.__dict__.pop("_degraded_unsync_ok", None)  # stale pairing flag
        group = process_group or self.process_group
        policy = self.sync_policy
        if policy is None and not self.__dict__.get("_sync_policy_explicit"):
            # inherit the process-wide default only when the metric never
            # expressed a choice: an explicit sync_policy=None means unguarded
            from torchmetrics_tpu._resilience.policy import default_sync_policy

            policy = default_sync_policy()
        self._cache = self._copy_state_dict()
        _sp = None
        if _OBS.tracing:
            _sp = _obs_trace.begin_span(
                "sync", type(self).__name__, mode="unguarded" if policy is None else "guarded"
            )
        _sp_err: Optional[BaseException] = None
        try:
            self._sync_guarded_or_not(dist_sync_fn, group, policy)
        except BaseException as err:
            _sp_err = err
            raise
        finally:
            if _sp is not None:
                _obs_trace.end_span(_sp, _sp_err)

    def _sync_guarded_or_not(self, dist_sync_fn: Callable, group: Any, policy: Any) -> None:
        """The committed half of :meth:`sync` (split out so the seam span
        brackets exactly the collective work, guarded attempts included)."""
        if policy is None:
            if _OBS.enabled:
                self._obs_call(
                    "sync_calls|mode=unguarded", "sync", "sync",
                    lambda: self._sync_dist(dist_sync_fn, process_group=group),
                )
            else:
                self._sync_dist(dist_sync_fn, process_group=group)
            self._is_synced = True
            return
        from torchmetrics_tpu._resilience.guard import guarded_metric_sync  # cached after first sync

        try:
            if _OBS.enabled:
                synced = self._obs_call(
                    "sync_calls|mode=guarded", "sync", "sync",
                    lambda: guarded_metric_sync(self, dist_sync_fn, group, policy),
                )
            else:
                synced = guarded_metric_sync(self, dist_sync_fn, group, policy)
        except Exception:
            # on_exhausted="raise" or a handshake mismatch: leave the metric
            # with its intact local state, never half-committed
            self._restore_state(self._cache)
            self._cache = None
            self._is_synced = False
            raise
        if synced:
            self._is_synced = True
        else:
            # degraded: retries exhausted — keep local-only state (the gather
            # phase is pure, but restore from the cache anyway for overridden
            # `_sync_dist` implementations that fuse gather and commit). The
            # flag lets a manual sync()/unsync() pairing stay graceful: the
            # paired unsync becomes a no-op instead of raising
            self._restore_state(self._cache)
            self._cache = None
            self._is_synced = False
            self.__dict__["_degraded_unsync_ok"] = True

    def _dist_gather(self, dist_sync_fn: Callable, process_group: Optional[Any] = None) -> Dict[str, Any]:
        """Gather every state across processes — pure read, no state mutation.

        Kept side-effect-free so the guarded sync path can run it on a
        watchdog worker thread: a timed-out, abandoned attempt that later
        completes has nothing it can corrupt.
        """
        input_dict = {attr: getattr(self, attr) for attr in self._reductions}
        for attr in self._reductions:
            # ring buffers gather their live rows like a pre-concatenated list
            if isinstance(input_dict[attr], RingBuffer):
                rb = input_dict[attr]
                input_dict[attr] = [rb.values()] if rb.num_valid else []
            # pre-concatenate list states to minimize number of all_gathers
            elif isinstance(input_dict[attr], list) and len(input_dict[attr]) >= 1:
                input_dict[attr] = [dim_zero_cat(input_dict[attr])]

        output_dict: Dict[str, Any] = {}
        for attr, value in input_dict.items():
            if isinstance(value, list):
                output_dict[attr] = _flatten_maybe([dist_sync_fn(v, process_group) for v in value])
            else:
                output_dict[attr] = dist_sync_fn(value, process_group)
        return output_dict

    def _sync_dist(self, dist_sync_fn: Callable = gather_all_tensors, process_group: Optional[Any] = None) -> None:
        """Reference ``metric.py:427-457``: pre-concat lists, gather, reduce."""
        self._commit_gathered(self._dist_gather(dist_sync_fn, process_group))

    def _commit_gathered(self, output_dict: Dict[str, Any]) -> None:
        """Reduce gathered per-process states into this metric's states."""
        for attr, reduction_fn in self._reductions.items():
            gathered = output_dict[attr]
            if isinstance(gathered, list) and len(gathered) == 0:
                setattr(self, attr, [])
                continue
            if _is_array(gathered[0]) and not isinstance(getattr(self, attr), (list, RingBuffer)):
                shapes = {g.shape for g in gathered}
                gathered = jnp.stack(gathered) if len(shapes) == 1 else gathered
            fn = _STR_REDUCTIONS.get(reduction_fn, reduction_fn) if isinstance(reduction_fn, str) else reduction_fn
            reduced = fn(gathered) if fn is not None else gathered
            setattr(self, attr, reduced)

    def unsync(self, should_unsync: bool = True) -> None:
        """Restore cached local (pre-sync) state (reference ``metric.py:534-554``)."""
        if not should_unsync:
            return
        if not self._is_synced:
            if self.__dict__.pop("_degraded_unsync_ok", False):
                return  # the paired sync() degraded to local-only: nothing to undo
            raise TorchMetricsUserError("The Metric has already been un-synced.")
        if self._cache is None:
            raise TorchMetricsUserError("The internal cache should exist to unsync the Metric.")
        self._restore_state(self._cache)
        self._is_synced = False
        self._cache = None

    class _SyncContext:
        def __init__(self, metric: "Metric", kwargs: Dict[str, Any], unsync_kwargs: Dict[str, Any]):
            self.metric = metric
            self.kwargs = kwargs
            self.unsync_kwargs = unsync_kwargs

        def __enter__(self) -> None:
            self.metric.sync(**self.kwargs)

        def __exit__(self, *exc: Any) -> None:
            if self.unsync_kwargs["should_unsync"] and self.metric._is_synced:
                self.metric.unsync()

    def sync_context(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        should_unsync: bool = True,
        distributed_available: Optional[Callable] = None,
    ) -> "_SyncContext":
        """Context manager: sync on enter, restore on exit (reference ``metric.py:556-591``)."""
        return Metric._SyncContext(
            self,
            {
                "dist_sync_fn": dist_sync_fn,
                "process_group": process_group,
                "should_sync": should_sync,
                "distributed_available": distributed_available,
            },
            {"should_unsync": should_unsync},
        )

    def to_spmd(self, *, mesh: Any = None, axis_name: str = "dp", **kwargs: Any) -> Any:
        """Hand this (fresh) metric to the SPMD in-graph engine.

        Returns a :class:`~torchmetrics_tpu._spmd.SpmdEngine` whose
        ``step(batch)`` lowers update + cross-device sync + compute into one
        donated compiled executable over a named device mesh — the
        TPU-native replacement for streaming ``update()`` and bolting an
        eager multi-host gather on afterwards. Gated by the eligibility
        manifest's ``in_graph_sync`` facet: host-bound classes raise
        :class:`~torchmetrics_tpu._spmd.InGraphSyncUnsupported` and keep the
        eager path. See README "SPMD in-graph engine".
        """
        from torchmetrics_tpu._spmd import SpmdEngine

        return SpmdEngine(self, mesh=mesh, axis_name=axis_name, **kwargs)

    def to_stream_pool(self, *, capacity: int = 8, **kwargs: Any) -> Any:
        """N independent streams of this (fresh) metric behind one vmapped step.

        Returns a :class:`~torchmetrics_tpu._streams.StreamPool` that stacks
        ``capacity`` independent copies of this metric's state along a
        leading slot axis and updates an arbitrary micro-batch of them per
        compiled call (``pool.update(stream_ids, *args)``), with O(1)
        ``attach``/``detach``/``reset(i)`` and per-stream ``compute(i)``.
        The metric itself is the *template*: it never accumulates. Gated by
        the eligibility manifest
        (:func:`~torchmetrics_tpu._analysis.manifest.stream_pool_eligible`);
        ineligible classes raise
        :class:`~torchmetrics_tpu._streams.StreamPoolUnsupported` and keep
        independent eager instances. See STREAMS.md.
        """
        from torchmetrics_tpu._streams import StreamPool

        return StreamPool(self, capacity=capacity, **kwargs)

    def sync_in_jit(
        self,
        state: Dict[str, Array],
        axis_name: str,
        axis_index_groups: Optional[Any] = None,
    ) -> Dict[str, Array]:
        """Functional in-jit sync of an explicit state dict over a mesh axis.

        ``axis_index_groups`` partitions the axis into independent subgroups
        (the in-jit form of ``process_group``). A flat ``process_group`` kwarg
        cannot be translated automatically — it names one subset, not a
        partition of the whole axis — so it must be spelled out here.
        """
        if axis_index_groups is None and self.process_group is not None:
            raise TorchMetricsUserError(
                "This metric was constructed with `process_group`, which the in-jit sync cannot infer a"
                " mesh partition from. Pass `axis_index_groups` explicitly, e.g."
                " `metric.sync_in_jit(state, 'dp', axis_index_groups=[[0, 1], [2, 3]])`."
            )
        return sync_in_jit(state, self._reductions, axis_name, axis_index_groups=axis_index_groups)

    # ------------------------------------------------------------ resilience
    def _validate_resilience_knobs(self) -> None:
        from torchmetrics_tpu._resilience.policy import NAN_POLICIES, SyncPolicy

        if self.sync_policy is not None and not isinstance(self.sync_policy, SyncPolicy):
            raise ValueError(
                f"Expected keyword argument `sync_policy` to be a `SyncPolicy` or None but got {self.sync_policy}"
            )
        if self.nan_policy not in NAN_POLICIES:
            raise ValueError(
                f"Expected keyword argument `nan_policy` to be one of {NAN_POLICIES} but got {self.nan_policy}"
            )

    def set_resilience_policy(self, sync_policy: Any = _UNSET, nan_policy: Any = _UNSET) -> "Metric":
        """Attach/replace resilience policies after construction (chainable).

        Only the arguments actually passed change; ``None`` explicitly
        disables a policy. Replacing the sync policy invalidates the cached
        handshake digest so the next guarded sync re-verifies structure.
        """
        old_sync, old_nan = self.sync_policy, self.nan_policy
        if sync_policy is not _UNSET:
            self.sync_policy = sync_policy
        if nan_policy is not _UNSET:
            self.nan_policy = nan_policy
        try:
            self._validate_resilience_knobs()
        except ValueError:
            # a rejected call must not leave the invalid value attached
            self.sync_policy, self.nan_policy = old_sync, old_nan
            raise
        if sync_policy is not _UNSET:
            # an explicit None here is an opt-out from the process default
            self._sync_policy_explicit = True
            self.__dict__.pop("_handshake_ok_digest", None)
        return self

    def resilience_report(self) -> Any:
        """Degradation telemetry for this metric (RESILIENCE.md).

        Returns a :class:`~torchmetrics_tpu._resilience.policy.ResilienceReport`
        with every recorded ``DegradationEvent`` (degraded syncs, quarantined
        batches, repaired restores). Events survive ``reset()`` — they are
        operational telemetry about the stream, not metric state.
        """
        from torchmetrics_tpu._resilience.policy import ResilienceReport

        return ResilienceReport(
            metric=type(self).__name__,
            events=tuple(self.__dict__.get("_resilience_events", ())),
            quarantined_updates=self.__dict__.get("_quarantined_updates", 0),
            dropped_events=self.__dict__.get("_resilience_events_dropped", 0),
        )

    def _record_degradation(self, kind: str, detail: str, attempts: int = 0) -> None:
        from torchmetrics_tpu._resilience.policy import MAX_EVENTS, DegradationEvent
        from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserWarning

        event = DegradationEvent(kind=kind, metric=type(self).__name__, detail=detail, attempts=attempts)
        if _OBS.enabled:
            # fold resilience degradations into the unified telemetry stream:
            # one bus for degradations, restores, churn, and heartbeats
            _telemetry_for(self).inc(f"degradations|kind={kind}")
            _BUS.publish(
                "degradation", type(self).__name__, f"{kind}: {detail}",
                data={"kind": kind, "attempts": attempts},
            )
        events = self.__dict__.setdefault("_resilience_events", [])
        events.append(event)
        if len(events) > MAX_EVENTS:
            # a permanently-degraded long-running job records one event per
            # sync: cap the log, keep the eviction count in the report
            evict = len(events) - MAX_EVENTS
            del events[:evict]
            self.__dict__["_resilience_events_dropped"] = (
                self.__dict__.get("_resilience_events_dropped", 0) + evict
            )
        rank_zero_warn(
            f"{type(self).__name__} degraded ({kind}): {detail} — see `Metric.resilience_report()`.",
            TorchMetricsUserWarning,
        )

    def _quarantine_snapshot(self) -> Dict[str, Any]:
        """Cheap rollback point for the NaN quarantine.

        jax array states are immutable, so they are kept by reference; list
        states need only a shallow copy (their elements cannot change, a
        rollback just restores the old list object's contents); ring buffers
        mutate in place and get a real copy.
        """
        snap: Dict[str, Any] = {}
        for attr in self._defaults:
            v = getattr(self, attr)
            if isinstance(v, RingBuffer):
                snap[attr] = v.copy()
            elif isinstance(v, list):
                snap[attr] = list(v)
            else:
                snap[attr] = v
        return snap

    def _guard_nonfinite_states(
        self, pre_state: Optional[Dict[str, Any]], pre_lens: Optional[Dict[str, int]] = None
    ) -> None:
        """NaN/Inf sentinel after an eager update (the ``nan_policy`` knob).

        ``raise`` surfaces the poisoned state immediately (state left as-is
        so it can be inspected; ``reset()`` clears it); ``warn`` only warns;
        ``quarantine`` rolls the whole update back — one bad batch then
        contributes nothing, mirroring how the compiled validate-args path
        drops violating batches.

        ``pre_lens`` (per-list-state pre-update lengths) limits the scan to
        the chunks this batch appended, keeping cat-state streams O(batch)
        per update. An update that rewrites *existing* list entries (rare:
        appends and whole-array rebinds are the idioms here) is outside the
        incremental scan's reach.
        """
        from torchmetrics_tpu._resilience.integrity import nonfinite_state_report
        from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserWarning

        if not self._defaults:
            # wrapper/compositional metrics hold their accumulators in child
            # metrics: the sentinel has nothing to guard, and silence here
            # would read as protection — say so once
            if not self.__dict__.get("_nan_policy_noop_warned"):
                self.__dict__["_nan_policy_noop_warned"] = True
                rank_zero_warn(
                    f"`nan_policy={self.nan_policy!r}` on {type(self).__name__} guards nothing:"
                    " this metric registers no states of its own (wrappers and compositions hold"
                    " their accumulators in child metrics). Set `nan_policy` on the wrapped"
                    " metric(s) instead.",
                    TorchMetricsUserWarning,
                )
            return
        bad = nonfinite_state_report(self, list_scan_from=pre_lens)
        if not bad:
            return
        desc = ", ".join(f"`{k}` ({v})" for k, v in sorted(bad.items()))
        batch = self.__dict__.get("_nan_seen_batches", self._update_count)
        policy = self.nan_policy
        if policy == "raise":
            raise RuntimeError(
                f"Non-finite values detected in state(s) {desc} of {type(self).__name__} after"
                f" guarded batch {batch} (`nan_policy='raise'`). The state is poisoned:"
                " every downstream `compute()` would silently return garbage. Call `reset()`,"
                " or use `nan_policy='quarantine'` to drop bad batches automatically."
            )
        if policy == "warn":
            rank_zero_warn(
                f"Non-finite values detected in state(s) {desc} of {type(self).__name__} after"
                f" guarded batch {batch} (`nan_policy='warn'`): downstream `compute()`"
                " results are now suspect.",
                TorchMetricsUserWarning,
            )
            return
        # quarantine: roll back this batch's contribution
        if pre_state is None:
            return
        self._restore_state(pre_state)
        still_bad = nonfinite_state_report(self, list_scan_from=pre_lens)
        if still_bad:
            # the poison predates this batch (policy enabled mid-stream):
            # rollback cannot recover — surface it instead of looping forever
            rank_zero_warn(
                f"State(s) {desc} of {type(self).__name__} were already non-finite before this"
                " update; `nan_policy='quarantine'` cannot recover a pre-poisoned metric —"
                " call `reset()`.",
                TorchMetricsUserWarning,
            )
            return
        self._update_count -= 1
        self._computed = None
        # `forward`'s reduce-state path consults this flag so a dropped batch
        # is not merged into the stashed global state either
        self.__dict__["_nan_last_quarantined"] = True
        self.__dict__["_quarantined_updates"] = self.__dict__.get("_quarantined_updates", 0) + 1
        if _OBS.enabled:
            _telemetry_for(self).inc("quarantined_batches")
        self._record_degradation(
            "nan_quarantine",
            detail=f"guarded batch {batch} produced non-finite state(s) {desc}; batch dropped",
        )

    # ------------------------------------------------------- compiled update
    def _fixed_shape_state_names(self, method_name: str) -> Optional[List[str]]:
        """State names for the compiled-update paths; None = warm up eagerly first.

        Lazily-allocated ring buffers learn their row shape from the first
        batch, so the first update must run eagerly before tracing.
        """
        def metric_like(v: Any) -> bool:
            # Metric subclasses AND collection-shaped delegates (MetricCollection,
            # wrapped collections) — anything with its own update/compute/reset
            # and a state registry
            return isinstance(v, Metric) or (
                hasattr(v, "update")
                and hasattr(v, "compute")
                and hasattr(v, "reset")
                and (hasattr(v, "_defaults") or hasattr(v, "_modules"))
            )

        def stateful_like(v: Any) -> bool:
            # duck-typed accumulators: the three method names but no registry.
            # Tracing an update that mutates such an object would freeze or
            # corrupt its state, so these also block the compiled paths — with
            # a distinct message, since they may be innocent user helpers.
            return (
                not isinstance(v, (Metric, jnp.ndarray, np.ndarray, RingBuffer))
                and hasattr(v, "update")
                and hasattr(v, "compute")
                and hasattr(v, "reset")
            )

        for attr, value in self.__dict__.items():
            if attr in ("update", "compute"):
                continue
            # metrics that delegate to child metrics (CompositionalMetric,
            # wrappers, task dicts) mutate state OUTSIDE self._defaults —
            # tracing their update would leak tracers into the children
            if isinstance(value, dict):
                children = list(value.values())
            elif isinstance(value, (list, tuple)):
                children = list(value)
            else:
                children = [value]
            if any(metric_like(v) for v in children):
                raise TorchMetricsUserError(
                    f"`{method_name}` is unsupported on {type(self).__name__}: it delegates to child"
                    f" metric(s) (`{attr}`) whose states live outside this metric's state registry."
                    " Call the compiled update on the component metrics directly."
                )
            if any(stateful_like(v) for v in children):
                raise TorchMetricsUserError(
                    f"`{method_name}` is unsupported on {type(self).__name__}: attribute `{attr}` looks"
                    " stateful (it exposes update/compute/reset) but is not a registered metric state."
                    " If `update()` mutates it, tracing would corrupt it; stream through the plain"
                    " `update()` path, or register its state with `add_state`."
                )
        names = list(self._defaults)
        warm_up = False
        for name in names:
            state = getattr(self, name)
            if isinstance(state, list):
                raise TorchMetricsUserError(
                    f"`{method_name}` requires fixed-shape states, but state `{name}` is an append-mode"
                    " list. Construct the metric with `cat_state_capacity=N` to bound it into a device"
                    " ring buffer, or stream through the plain `update()` path."
                )
            if isinstance(state, RingBuffer) and not state.initialized:
                warm_up = True
        return None if warm_up else names

    def _traced_update(self, names: List[str], states: Dict[str, Any], args: tuple, kwargs: Dict[str, Any]):
        """Run the raw update on temporarily-bound (possibly traced) states."""
        saved = {n: getattr(self, n) for n in names}
        try:
            for n in names:
                object.__setattr__(self, n, states[n])
            # named_scope runs at TRACE time only (compiled replays carry the
            # name in HLO metadata for free), so device profiles attribute
            # this body's ops to `ClassName.update`
            with _obs_scopes.named_scope(f"{type(self).__name__}.update"):
                self.update.__wrapped__(*args, **kwargs)
            new_states = {n: getattr(self, n) for n in names}
            if self._dtype_policy is not None:
                # mirror _wrap_update's post-update cast so compiled carries
                # keep the declared dtype (scan requires stable carry types)
                new_states = {
                    n: (
                        v.astype(self._dtype_policy)
                        if _is_array(v) and jnp.issubdtype(v.dtype, jnp.floating)
                        else v
                    )
                    for n, v in new_states.items()
                }
            return new_states
        finally:
            for n, v in saved.items():
                object.__setattr__(self, n, v)

    @staticmethod
    def _split_batch_args(method_name: str, args: tuple, kwargs: Dict[str, Any]):
        """Partition ``(args, kwargs)`` leaves into traced arrays vs static values.

        Python-level flags (e.g. ``FrechetInceptionDistance.update``'s
        ``real=True``) must stay static so ``if flag:`` control flow inside
        update keeps working under trace; arrays become jit inputs.  Returns
        ``(treedef, dynamic_leaves, statics_key)`` where ``statics_key`` is a
        hashable ``(position, value)`` tuple for the compile cache.
        """
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        dynamic = [leaf for leaf in leaves if _is_array(leaf)]
        statics = tuple((i, leaf) for i, leaf in enumerate(leaves) if not _is_array(leaf))
        try:
            hash(statics)
        except TypeError:
            raise TorchMetricsUserError(
                f"`{method_name}` arguments must be arrays or hashable static values, got"
                f" {[type(leaf).__name__ for _, leaf in statics]}; use the plain `update()` path."
            ) from None
        return treedef, dynamic, statics

    @staticmethod
    def _merge_batch_args(treedef, dynamic: List[Any], statics) -> tuple:
        leaves: List[Any] = []
        static_map = dict(statics)
        dyn_iter = iter(dynamic)
        for i in range(treedef.num_leaves):
            leaves.append(static_map[i] if i in static_map else next(dyn_iter))
        return jax.tree_util.tree_unflatten(treedef, leaves)

    # compile-cache attribute -> the churn detector's compile-event kind; the
    # AOT disk cache names artifacts by the same kinds so `tools/aot_cache.py
    # list` output and `telemetry_report()` churn lines read as one vocabulary
    _AOT_KINDS = {
        "_auto_update_fn": "auto_update",
        "_auto_forward_fn": "auto_forward",
        "_jit_update_fn": "jit_update",
        "_scan_update_fn": "scan_update",
    }

    def _compiled_update(self, cache_name: str, key, build) -> Callable:
        cache = self.__dict__.setdefault(cache_name, {})
        # the dtype policy is baked into the trace (states are cast inside
        # `_traced_update`), so it must participate in the cache key — a
        # `set_dtype` call after a compile would otherwise replay stale casts
        policy = None if self._dtype_policy is None else jnp.dtype(self._dtype_policy).name
        key = (key, policy)
        if key not in cache:
            fn = jax.jit(build())
            if _AOT.active or _OBS.profiling:
                # route trace+compile through the persistent executable cache:
                # a warm artifact loads instead of tracing, a cold one is
                # serialized after its first compile for the next process.
                # With profiling on (and no AOT directory) the dispatcher is
                # memory-only — it exists so compile time and XLA's
                # cost_analysis() are captured at the one place the compiled
                # object is in hand (`_AotDispatch._resolve_inner`).
                from torchmetrics_tpu._aot.cache import wrap_executable

                fn = wrap_executable(
                    fn,
                    owner=f"{type(self).__module__}.{type(self).__qualname__}",
                    kind=self._AOT_KINDS.get(cache_name, cache_name),
                    key_repr=repr(key),
                    telem_obj=self,
                )
            if _OBS.enabled:
                # trace+lowering happen lazily on the first invocation: shim
                # that one call to time it, then self-replace with the raw
                # executable so steady-state dispatch pays nothing
                fn = self._obs_timed_first_call(cache, key, fn)
            cache[key] = fn
        return cache[key]

    def _obs_timed_first_call(self, cache: Dict, key: Any, fn: Callable) -> Callable:
        """Wrap a fresh jitted callable to record its first-call (trace +
        lower + execute) wall time, attributed to this metric's telemetry."""

        def timed(*args: Any, **kwargs: Any) -> Any:
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            elapsed = time.perf_counter() - t0
            cache[key] = fn
            if _OBS.enabled:
                telem = _telemetry_for(self)
                telem.inc("trace_seconds", elapsed)
                telem.observe("trace", elapsed)
            return out

        return timed

    # ---------------------------------------------------- transparent auto-jit
    _AUTO_MAX_SIGNATURES = 8

    def _auto_eligible(self) -> bool:
        """Base gate for transparent compilation of ``update``/``forward``.

        Metrics with ``validate_args=True`` compile when they provide a
        traced validator (:meth:`_traced_value_flags`) — the per-batch value
        checks then run fused inside the XLA step and surface asynchronously
        (see :meth:`_check_pending_violations`) — OR when the static
        eligibility prover certified the class *metadata-only* (verdict (a)
        in ``_analysis/eligibility.json``: every check on its eager path
        depends only on shapes/dtypes/ctor args, which trace time re-runs, so
        compiling cannot skip a check and no validator is needed). Otherwise
        the eager path keeps running the host-side checks. ``compute_on_cpu``
        implies host-resident growing states, which the compiled path cannot
        maintain.
        """
        return (
            self.auto_compile
            and not self._auto_disabled
            and not self.compute_on_cpu
            # the NaN sentinel is a per-batch host readback over the states —
            # it must observe every eager update, so it pins the eager path
            and self.nan_policy is None
            and (
                getattr(self, "validate_args", None) is not True
                or self._supports_traced_validation()
                or self._metadata_only_validation()
            )
        )

    def _metadata_only_validation(self) -> bool:
        """Eligibility-manifest gate: proven metadata-only class.

        Per-class memoization lives in the manifest module so the runtime
        toggle (``set_eligibility_enabled``) invalidates in one place.
        """
        return compiled_validation_eligible(type(self))

    def _traced_value_flags(self, *args: Any, **kwargs: Any) -> Optional[Tuple]:
        """Traceable value-dependent input validation: ``(messages, flags)``.

        Subclasses that support compiled validation return a static tuple of
        violation messages and a same-length boolean array (``flags[i]=True``
        means the batch violates check ``i``), computed with jnp ops only —
        no host synchronization. The message tuple must not depend on the
        argument values. An optional third element gives per-check severities
        (``"error"`` — default — or ``"warn"``): error checks drop the
        violating batch's contribution and raise at the next sync point;
        warn checks keep the batch and only warn (the traced twin of
        warn-and-continue eager checks like the aggregators' NaN strategy).
        The base returns ``None``: metrics without a traced validator keep
        the eager path whenever ``validate_args=True`` (unless the
        eligibility prover certified their validation metadata-only).
        """
        return None

    def _supports_traced_validation(self) -> bool:
        sup = self._traced_validation_supported
        if sup is None:
            sup = type(self)._traced_value_flags is not Metric._traced_value_flags
            self._traced_validation_supported = sup
        return sup

    def _auto_validate(self) -> bool:
        """True when compiled updates must carry the fused value checks."""
        return getattr(self, "validate_args", None) is True and self._supports_traced_validation()

    @staticmethod
    def _split_value_flags(res) -> Tuple[Tuple[str, ...], Any, Tuple[str, ...]]:
        """Normalize a ``_traced_value_flags`` result to (msgs, flags, sevs).

        Severities are validated loudly: an unknown string would otherwise
        make a fired flag match neither the error nor the warn filter and
        the violation would vanish silently.
        """
        msgs, flags = res[0], res[1]
        sevs = tuple(res[2]) if len(res) > 2 else tuple("error" for _ in msgs)
        bad = [s for s in sevs if s not in ("error", "warn")]
        if bad or len(sevs) != len(msgs):
            raise TorchMetricsUserError(
                "`_traced_value_flags` severities must be 'error' or 'warn', one per message;"
                f" got {sevs!r} for {len(tuple(msgs))} message(s)"
            )
        return tuple(msgs), flags, sevs

    def _prime_violation_state(self, treedef, dynamic: List[Any], statics) -> bool:
        """Learn the violation-message vector (once) before the first compile.

        Returns True when the metric has value checks to fuse; False when its
        validation is metadata-only (compiled updates then skip the flag
        carry entirely).
        """
        if self._viol_msgs is None:
            a, kw = self._merge_batch_args(treedef, dynamic, statics)
            msgs, _, sevs = self._split_value_flags(self._traced_value_flags(*a, **kw))
            self._viol_msgs = msgs
            self._viol_sevs = sevs
        elif self._viol_sevs is None:
            # metric unpickled from a pre-severity version with msgs already
            # primed: backfill so the trace-time consistency check holds
            self._viol_sevs = tuple("error" for _ in self._viol_msgs)
        if self._viol_flags is None and self._viol_msgs:
            object.__setattr__(self, "_viol_flags", jnp.zeros(len(self._viol_msgs), dtype=bool))
        return bool(self._viol_msgs)

    def _check_pending_violations(self) -> None:
        """Surface value-check violations recorded by compiled updates.

        With auto-compile the ``validate_args=True`` value checks run fused
        inside the XLA step and OR-accumulate into a device-resident flag
        vector — a per-batch host readback would serialize the TPU stream
        (and costs a full RTT through a remote-device tunnel). Violations
        therefore surface at the next host synchronization point — the next
        eager ``update``/``forward``, ``compute()``, or ``reset()`` — the
        same way CUDA device-side asserts surface at the next sync. The
        first call with any argument signature always validates eagerly, so
        single-batch misuse still raises immediately with the reference's
        exact message.
        """
        flags = self._viol_flags
        if flags is None:
            return
        vals = np.asarray(flags)
        if vals.any():
            sevs = self._viol_sevs or tuple("error" for _ in self._viol_msgs)
            errors = [m for m, s, v in zip(self._viol_msgs, sevs, vals) if v and s == "error"]
            warns = [m for m, s, v in zip(self._viol_msgs, sevs, vals) if v and s == "warn"]
            object.__setattr__(self, "_viol_flags", jnp.zeros_like(flags))
            if _OBS.enabled:
                telem = _telemetry_for(self)
                if errors:
                    telem.inc("deferred_violations|severity=error", len(errors))
                if warns:
                    telem.inc("deferred_violations|severity=warn", len(warns))
            for msg in warns:
                rank_zero_warn(
                    f"{msg} (surfaced asynchronously: this warn-severity check ran fused inside"
                    " the compiled update)",
                    UserWarning,
                )
            if errors:
                raise RuntimeError(
                    f"{errors[0]} (raised asynchronously: with `auto_compile` the `validate_args=True`"
                    " value checks run fused inside the compiled update and surface at the next host"
                    " synchronization point)"
                )

    def _auto_state_names(self, method_name: str) -> Optional[List[str]]:
        """Fixed-shape state names for the auto paths (cached when stable)."""
        names = self._auto_names
        if names is not None:
            return names
        names = self._fixed_shape_state_names(method_name)
        if names is None:  # lazily-shaped ring buffer: warm up eagerly first
            return None
        if not any(isinstance(getattr(self, n), RingBuffer) for n in names):
            # ring-buffer states go back to lazy after reset(), so only
            # plain-array state sets can skip the re-check
            self._auto_names = names
        return names

    def _auto_signature(self, args: tuple, kwargs: Dict[str, Any], method_name: str = "update"):
        """Hashable (structure, statics, shapes/dtypes) argument-signature key.

        The single composition point for every compiled-path cache key
        (auto update/forward, ``jit_update``, ``scan_update``, ring-buffer
        append-count replay) — keep it that way.
        """
        treedef, dynamic, statics = self._split_batch_args(method_name, args, kwargs)
        sig = (treedef, statics, tuple((tuple(d.shape), str(d.dtype)) for d in dynamic))
        return sig, treedef, dynamic, statics

    def _try_auto_update(self, args: tuple, kwargs: Dict[str, Any]) -> bool:
        """Route a repeat-signature ``update()`` through the compiled path.

        Returns True when the update was fully handled. Any failure —
        unhashable statics, list states, delegating metrics, untraceable
        update bodies — permanently disables the auto path for this instance
        and falls back to the eager wrapped update.
        """
        if not self._auto_eligible():
            return False
        try:
            sig, treedef, dynamic, statics = self._auto_signature(args, kwargs)
        except (TorchMetricsUserError, TypeError) as err:
            self._auto_disabled = True
            if _OBS.enabled:
                self._obs_auto_disabled(f"unhashable/unsupported update arguments: {err}")
            return False
        if not dynamic:
            # pure-static call (e.g. `update(1.0)` streams of python scalars):
            # the values live in the compile key, so compiling buys nothing
            return False
        seen = self._auto_sigs
        if sig not in seen:
            if len(seen) >= self._AUTO_MAX_SIGNATURES:
                if _OBS.enabled:
                    # the signature cache is saturated and shapes keep
                    # churning: every further new shape streams eagerly —
                    # exactly the pathology the churn counters exist to name
                    # (built=False: no executable is ever built for these)
                    _telemetry_for(self).inc("signature_overflow")
                    self._obs_compile_event("auto_update", treedef, statics, sig[2], built=False)
                return False  # shape churn: keep known sigs compiled, new ones eager
            seen[sig] = 0
            if _OBS.enabled:
                # a new signature means a new compiled executable (traced on
                # the first replay): report the cache key for churn tracking
                self._obs_compile_event("auto_update", treedef, statics, sig[2])
            return False  # first occurrence runs eagerly (validation + warm-up)
        try:
            names = self._auto_state_names("update")
        except TorchMetricsUserError as err:
            self._auto_disabled = True
            if _OBS.enabled:
                self._obs_auto_disabled(f"states unsupported by the compiled path: {err}")
            return False
        if names is None:
            return False
        states = {n: getattr(self, n) for n in names}
        validate = self._auto_validate()
        if validate:
            try:
                validate = self._prime_violation_state(treedef, dynamic, statics)
            except Exception:
                self._auto_disabled = True
                return False

        def build():
            def _pure(states_, viol, dyn):
                a, kw = self._merge_batch_args(treedef, dyn, statics)
                new_states_ = self._traced_update(names, states_, a, kw)
                if validate:
                    msgs, flags, sevs = self._split_value_flags(self._traced_value_flags(*a, **kw))
                    if msgs != self._viol_msgs or sevs != self._viol_sevs:  # static, checked at trace time
                        raise TorchMetricsUserError(
                            "traced validation messages changed across argument signatures"
                        )
                    viol = viol | flags
                    # a violating batch must not contaminate the state — the
                    # eager/reference path raises before committing, so the
                    # compiled path drops the batch's contribution instead.
                    # Warn-severity checks keep the batch (their eager twin
                    # warns and continues), so only error flags gate the drop
                    err_mask = np.array([s == "error" for s in sevs], dtype=bool)
                    bad = jnp.any(flags & jnp.asarray(err_mask)) if err_mask.any() else jnp.zeros((), jnp.bool_)
                    new_states_ = jax.tree_util.tree_map(
                        lambda old, new: jnp.where(bad, old, new), states_, new_states_
                    )
                return new_states_, viol

            return _pure

        obs_sample = False
        prof = _OBS.profiling
        t0 = 0.0
        if _OBS.enabled:
            obs_sample = _telemetry_for(self).sample_due("update_compiled")
        if obs_sample or prof:
            # profiling times EVERY step (cost accounting must add up);
            # latency sampling stays 1-in-N
            t0 = time.perf_counter()
        try:
            # the fused-flag marker lets traced bodies that need a raise-or-
            # drop escape hatch (aggregator NaN "error") know their violation
            # will be carried by the flag vector instead of silently lost
            if validate:
                self.__dict__["_fused_flags_tracing"] = True
            try:
                fn = self._compiled_update("_auto_update_fn", (treedef, statics, validate), build)
                if _OBS.enabled and _OBS.profile_scopes:
                    with _obs_scopes.annotation(f"{type(self).__name__}.update[compiled]"):
                        new_states, new_viol = fn(states, self._viol_flags if validate else None, dynamic)
                else:
                    new_states, new_viol = fn(states, self._viol_flags if validate else None, dynamic)
            finally:
                self.__dict__.pop("_fused_flags_tracing", None)
        except Exception as err:
            self._auto_disabled = True
            if _OBS.enabled:
                self._obs_auto_disabled(f"compiled update failed: {type(err).__name__}: {err}")
            return False
        if obs_sample or prof:
            elapsed = time.perf_counter() - t0
            if prof:
                _PROF_LEDGER.record_step("update_compiled", type(self).__name__, elapsed)
        if _OBS.enabled:
            telem = _telemetry_for(self)
            telem.inc("update_calls|path=auto_compiled")
            if obs_sample:
                telem.observe("update_compiled", elapsed)
        if validate:
            object.__setattr__(self, "_viol_flags", new_viol)
        seen[sig] += 1
        self._computed = None
        self._update_count += 1
        self._commit_compiled_states(names, states, new_states, sig)
        return True

    def _traced_compute(self, names: List[str], states: Dict[str, Any]) -> Any:
        """Run the raw (unwrapped) compute on temporarily-bound traced states."""
        saved = {n: getattr(self, n) for n in names}
        try:
            for n in names:
                object.__setattr__(self, n, states[n])
            with _obs_scopes.named_scope(f"{type(self).__name__}.compute"):
                return self.compute.__wrapped__()
        finally:
            for n, v in saved.items():
                object.__setattr__(self, n, v)

    def _auto_forward_mergeable(self, names: List[str]) -> bool:
        """True when every state merges functionally under trace (no growing shapes)."""
        for n in names:
            if isinstance(getattr(self, n), RingBuffer):
                return False
            reduce_fn = self._reductions[n]
            if not (reduce_fn in ("sum", "mean", "max", "min") or callable(reduce_fn)):
                return False
        return True

    def _try_auto_forward(self, args: tuple, kwargs: Dict[str, Any]):
        """Compiled ``forward`` for reduce-state metrics: one XLA call computes
        the batch value AND merges the batch state into the global state —
        replacing the eager stash/reset/update/compute/merge dance
        (reference ``metric.py:353-391``) with a single device dispatch.
        """
        if self._auto_forward_disabled or not self._auto_eligible():
            return False, None
        try:
            sig, treedef, dynamic, statics = self._auto_signature(args, kwargs)
        except (TorchMetricsUserError, TypeError) as err:
            self._auto_forward_disabled = True
            if _OBS.enabled:
                self._obs_auto_disabled(f"unhashable/unsupported forward arguments: {err}")
            return False, None
        if not dynamic:
            return False, None
        seen = self._auto_fwd_sigs
        if sig not in seen:
            if len(seen) >= self._AUTO_MAX_SIGNATURES:
                if _OBS.enabled:
                    _telemetry_for(self).inc("signature_overflow")
                    self._obs_compile_event("auto_forward", treedef, statics, sig[2], built=False)
                return False, None
            seen[sig] = 0
            if _OBS.enabled:
                self._obs_compile_event("auto_forward", treedef, statics, sig[2])
            return False, None
        try:
            names = self._auto_state_names("forward")
        except TorchMetricsUserError as err:
            self._auto_forward_disabled = True
            if _OBS.enabled:
                self._obs_auto_disabled(f"states unsupported by the compiled forward: {err}")
            return False, None
        if names is None or not self._auto_forward_mergeable(names):
            self._auto_forward_disabled = True
            if names is not None and _OBS.enabled:
                self._obs_auto_disabled("state reductions do not merge functionally under trace")
            return False, None
        states = {n: getattr(self, n) for n in names}
        reductions = {n: self._reductions[n] for n in names}
        defaults = {n: jnp.asarray(self._defaults[n]) for n in names}
        validate = self._auto_validate()
        if validate:
            try:
                validate = self._prime_violation_state(treedef, dynamic, statics)
            except Exception:
                self._auto_forward_disabled = True
                return False, None

        def build():
            def _pure(states_, viol, dyn, prev_count):
                a, kw = self._merge_batch_args(treedef, dyn, statics)
                batch = self._traced_update(names, defaults, a, kw)
                batch_val = _squeeze_if_scalar(self._traced_compute(names, batch))
                bad = jnp.zeros((), dtype=jnp.bool_)
                if validate:
                    msgs, flags, sevs = self._split_value_flags(self._traced_value_flags(*a, **kw))
                    if msgs != self._viol_msgs or sevs != self._viol_sevs:  # static, checked at trace time
                        raise TorchMetricsUserError(
                            "traced validation messages changed across argument signatures"
                        )
                    viol = viol | flags
                    # warn-severity checks never poison the batch value or
                    # drop the merge — only error flags do
                    err_mask = np.array([s == "error" for s in sevs], dtype=bool)
                    bad = jnp.any(flags & jnp.asarray(err_mask)) if err_mask.any() else jnp.zeros((), jnp.bool_)

                    def _poison(v):
                        # the eager/reference contract raises and never
                        # yields a value for an invalid batch; the compiled
                        # path can't raise mid-stream, so the returned batch
                        # value is visibly poisoned instead (NaN / INT_MIN)
                        if jnp.issubdtype(v.dtype, jnp.inexact):
                            return jnp.where(bad, jnp.nan, v)
                        if jnp.issubdtype(v.dtype, jnp.integer):
                            return jnp.where(bad, jnp.iinfo(v.dtype).min, v)
                        return v

                    batch_val = jax.tree_util.tree_map(_poison, batch_val)
                # the count carries as int32 (exact for any realistic stream,
                # unlike a f32 carry which saturates at 2^24) and converts to
                # float only where the running-mean weights need it
                prev_f = prev_count.astype(jnp.float32)
                merged = {}
                for n in names:
                    reduce_fn = reductions[n]
                    g, loc = states_[n], batch[n]
                    if reduce_fn == "sum":
                        merged[n] = g + loc
                    elif reduce_fn == "mean":
                        merged[n] = (prev_f * g + loc) / (prev_f + 1.0)
                    elif reduce_fn == "max":
                        merged[n] = jnp.maximum(g, loc)
                    elif reduce_fn == "min":
                        merged[n] = jnp.minimum(g, loc)
                    else:
                        merged[n] = reduce_fn(jnp.stack([g, loc]))
                    if validate:
                        # violating batches contribute nothing (the eager
                        # path raises before merging) — state and count both
                        # hold so post-reset streams resume uncontaminated
                        merged[n] = jnp.where(bad, g, merged[n])
                return merged, batch_val, viol, prev_count + jnp.where(bad, 0, 1).astype(prev_count.dtype)

            return _pure

        # the update count rides along as a device scalar so steady-state
        # streaming never pays a per-call host->device transfer for it
        cnt = self.__dict__.get("_auto_cnt")
        if cnt is None or cnt[0] != self._update_count:
            cnt = (self._update_count, jnp.int32(self._update_count))
        obs_sample = False
        prof = _OBS.profiling
        t0 = 0.0
        if _OBS.enabled:
            obs_sample = _telemetry_for(self).sample_due("forward_compiled")
        if obs_sample or prof:
            t0 = time.perf_counter()
        try:
            if validate:
                self.__dict__["_fused_flags_tracing"] = True
            try:
                fn = self._compiled_update("_auto_forward_fn", (treedef, statics, validate), build)
                if _OBS.enabled and _OBS.profile_scopes:
                    with _obs_scopes.annotation(f"{type(self).__name__}.forward[compiled]"):
                        new_states, batch_val, new_viol, new_cnt = fn(
                            states, self._viol_flags if validate else None, dynamic, cnt[1]
                        )
                else:
                    new_states, batch_val, new_viol, new_cnt = fn(
                        states, self._viol_flags if validate else None, dynamic, cnt[1]
                    )
            finally:
                self.__dict__.pop("_fused_flags_tracing", None)
        except Exception as err:
            self._auto_forward_disabled = True
            if _OBS.enabled:
                self._obs_auto_disabled(f"compiled forward failed: {type(err).__name__}: {err}")
            return False, None
        if obs_sample or prof:
            elapsed = time.perf_counter() - t0
            if prof:
                _PROF_LEDGER.record_step("forward_compiled", type(self).__name__, elapsed)
        if _OBS.enabled:
            telem = _telemetry_for(self)
            telem.inc("update_calls|path=forward_compiled")
            if obs_sample:
                telem.observe("forward_compiled", elapsed)
        if validate:
            object.__setattr__(self, "_viol_flags", new_viol)
        object.__setattr__(self, "_auto_cnt", (self._update_count + 1, new_cnt))
        seen[sig] += 1
        self._update_count += 1
        for n in names:
            object.__setattr__(self, n, new_states[n])
        self._computed = None
        self._is_synced = False
        self._should_unsync = True
        self._to_sync = self.sync_on_compute
        return True, batch_val

    def _commit_compiled_states(self, names: List[str], prior: Dict[str, Any], new_states: Dict[str, Any], sig) -> None:
        """Bind post-compiled-update states, restoring ring-buffer bookkeeping.

        A traced ring push cannot run the host-side overflow check, so the
        appended row count per argument signature is measured once (a single
        device readback) and replayed thereafter — the capacity-overflow
        warning keeps firing even for streams that never touch the eager path.
        """
        for n in names:
            nb = new_states[n]
            ob = prior.get(n)
            if isinstance(nb, RingBuffer) and isinstance(ob, RingBuffer):
                nb._warned_overflow = ob._warned_overflow
                if ob._host_count is None:
                    nb._sync_host_count(None)
                else:
                    deltas = self.__dict__.setdefault("_ring_count_deltas", {})
                    key = (n, sig)
                    if key not in deltas:
                        deltas[key] = int(nb.count) - ob._host_count
                    nb._sync_host_count(ob._host_count + deltas[key])
            object.__setattr__(self, n, nb)

    def precompile(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Warm the compiled default update path for this argument signature.

        Runs the REAL update machinery twice on stashed state — the first
        pass registers the argument signature and runs eager validation, the
        second builds (or, with an AOT cache directory set via
        ``TM_TPU_AOT_CACHE`` / ``set_aot_cache``, loads from disk) the
        compiled executable — then restores the metric exactly as it was:
        states, update count, cached compute, and deferred-violation flags
        are untouched by the warm-up batch. The registered signature
        persists, so the FIRST real ``update()`` with matching shapes
        dispatches straight to the warm executable.

        Returns a small report: ``engaged`` (the compiled path is armed),
        and ``reason`` when it is not (eager-pinned class, prior trace
        failure, unsupported arguments).
        """
        report: Dict[str, Any] = {"engaged": False, "reason": None}
        if not self._auto_eligible():
            report["reason"] = (
                "auto path disabled for this instance"
                if (self._auto_disabled or not self.auto_compile)
                else "class streams eagerly (not certified for the compiled default path)"
            )
            return report
        global_state = self._copy_state_dict()
        saved_count = self._update_count
        saved_computed = self._computed
        saved_viol = self._viol_flags
        saved_nan_batches = self.__dict__.get("_nan_seen_batches")
        self.__dict__["_journal_suspend"] = True
        try:
            import warnings as _warnings

            with _warnings.catch_warnings():
                _warnings.simplefilter("ignore")
                # pre-register the argument signature: the warm-up update then
                # dispatches straight through the compiled path (where the AOT
                # cache can serve it) instead of paying the ordinary
                # first-call-eager pass — for certified classes the prover
                # guarantees the compiled path loses no checks, which is the
                # same contract the second-call compile relies on
                try:
                    sig, treedef, dynamic, statics = self._auto_signature(args, kwargs)
                except (TorchMetricsUserError, TypeError):
                    sig = dynamic = None
                if dynamic and sig not in self._auto_sigs:
                    if len(self._auto_sigs) >= self._AUTO_MAX_SIGNATURES:
                        # honor the same saturation bound as _try_auto_update:
                        # warming N shape variants must not grow an unbounded
                        # executable cache — past the cap this signature
                        # streams eagerly like any other overflow shape
                        if _OBS.enabled:
                            _telemetry_for(self).inc("signature_overflow")
                        report["reason"] = (
                            f"signature cache saturated ({self._AUTO_MAX_SIGNATURES} shapes):"
                            " this signature streams eagerly"
                        )
                        return report
                    self._auto_sigs[sig] = 0
                    if _OBS.enabled:
                        self._obs_compile_event("auto_update", treedef, statics, sig[2])
                self.update(*args, **kwargs)
                if "_auto_update_fn" not in self.__dict__ and not self._auto_disabled:
                    # lazily-shaped states (ring buffers) warm up eagerly on
                    # the first pass; the second pass builds the executable
                    self.update(*args, **kwargs)
        finally:
            self.__dict__.pop("_journal_suspend", None)
            self._update_count = saved_count
            self._computed = saved_computed
            object.__setattr__(self, "_viol_flags", saved_viol)
            if saved_nan_batches is None:
                self.__dict__.pop("_nan_seen_batches", None)
            else:
                self.__dict__["_nan_seen_batches"] = saved_nan_batches
            self._restore_state(global_state)
        report["engaged"] = "_auto_update_fn" in self.__dict__ and not self._auto_disabled
        if not report["engaged"]:
            report["reason"] = "update did not compile (see telemetry `auto_path_disabled` events)"
        return report

    def jit_update(self, *args: Any, **kwargs: Any) -> None:
        """``update()`` compiled into a single XLA computation.

        TPU-native fast path for eager per-batch streaming: the whole state
        transition (format + update + reduction) is traced once per
        argument-shape and replayed as one device executable, removing the
        per-op python dispatch that dominates `update()`'s cost.  Semantics
        match ``update()`` for array/ring-buffer states, except value-dependent
        input validation is skipped after trace time (as under any jit —
        equivalent to ``validate_args=False``).  Array arguments are traced
        (retrace per distinct shape/dtype); non-array arguments — flags like
        ``real=True`` — stay static, so python control flow on them works.
        """
        names = self._fixed_shape_state_names("jit_update")
        if names is None:  # uninitialized ring buffer: first batch allocates eagerly
            self.update(*args, **kwargs)
            return
        sig, treedef, dynamic, statics = self._auto_signature(args, kwargs, "jit_update")

        def build():
            def _pure(states, dyn):
                a, kw = self._merge_batch_args(treedef, dyn, statics)
                return self._traced_update(names, states, a, kw)

            return _pure

        fn = self._compiled_update("_jit_update_fn", (treedef, statics), build)
        states = {n: getattr(self, n) for n in names}
        if _OBS.enabled:
            self._obs_compile_event("jit_update", treedef, statics, sig[2])
            new_states = self._obs_call("update_calls|path=jit", "update_jit", "jit_update", lambda: fn(states, dynamic))
        else:
            new_states = fn(states, dynamic)
        self._computed = None
        self._update_count += 1
        self._commit_compiled_states(names, states, new_states, sig)
        self._journal_record("update", args, kwargs)

    def scan_update(self, *args: Any, **kwargs: Any) -> None:
        """Consume a whole stacked stream of batches in one ``lax.scan``.

        Every positional/keyword ARRAY argument carries a leading stream axis
        of equal length S (non-array arguments stay static and apply to every
        step); the call is equivalent to S successive ``update()`` calls but
        compiles to ONE device executable with zero per-batch dispatch — the
        deployment shape `bench.py`'s fused headline number measures.  Same
        constraints as :meth:`jit_update`.
        """
        names = self._fixed_shape_state_names("scan_update")
        if names is None:  # uninitialized ring buffer: peel one batch eagerly
            first = jax.tree_util.tree_map(lambda x: x[0] if _is_array(x) else x, (args, kwargs))
            self.update(*first[0], **first[1])
            rest = jax.tree_util.tree_map(lambda x: x[1:] if _is_array(x) else x, (args, kwargs))
            arr = [x for x in jax.tree_util.tree_leaves(rest) if _is_array(x)]
            if arr and arr[0].shape[0]:
                self.scan_update(*rest[0], **rest[1])
            return
        sig, treedef, dynamic, statics = self._auto_signature(args, kwargs, "scan_update")
        if not dynamic:
            raise TorchMetricsUserError("`scan_update` needs at least one array argument with a stream axis")

        def build():
            def _scan(states, dyn):
                def step(carry, dyn_slice):
                    a, kw = self._merge_batch_args(treedef, dyn_slice, statics)
                    return self._traced_update(names, carry, a, kw), None

                return jax.lax.scan(step, states, dyn)[0]

            return _scan

        fn = self._compiled_update("_scan_update_fn", (treedef, statics), build)
        n_steps = int(dynamic[0].shape[0])
        states = {n: getattr(self, n) for n in names}
        if _OBS.enabled:
            self._obs_compile_event("scan_update", treedef, statics, sig[2])
            new_states = self._obs_call("update_calls|path=scan", "update_scan", "scan_update", lambda: fn(states, dynamic))
            _telemetry_for(self).inc("scan_steps", n_steps)
        else:
            new_states = fn(states, dynamic)
        self._computed = None
        self._update_count += n_steps
        self._commit_compiled_states(names, states, new_states, sig)
        # "scan" replays through scan_update: the args carry a leading
        # stream axis that plain update() must not see as one batch
        self._journal_record("scan", args, kwargs)

    def merge_state(self, incoming: Union["Metric", Dict[str, Any]]) -> None:
        """Merge another metric's (or raw state dict's) state into this one.

        TPU-native first-class API: the same declared per-state reductions used
        by forward accumulation and distributed sync.

        A raw state dict that carries an integrity block (saved with
        ``state_dict(integrity=True)``) is verified before anything merges —
        checksum mismatches or NaN-poisoned payloads raise
        :class:`~torchmetrics_tpu._resilience.errors.StateCorruptionError`
        instead of silently folding a corrupt contribution into this metric.
        """
        if isinstance(incoming, Metric):
            if type(incoming) is not type(self):
                raise TorchMetricsUserError(
                    f"Cannot merge state of {type(incoming).__name__} into {type(self).__name__}"
                )
            incoming_state = incoming.metric_state
            incoming_count = incoming._update_count
        else:
            from torchmetrics_tpu._resilience import integrity as _integrity

            meta = incoming.get(_integrity.integrity_key(""))
            if meta is not None:
                # the dict announced verifiability: honoring the block is not
                # optional, or a bit-flipped payload merges as clean data
                corrupted = _integrity.verify_states(
                    incoming, "", meta, type(self).__name__, include_missing=True
                )
                if corrupted:
                    _integrity.raise_corrupted(type(self).__name__, corrupted)
            incoming_state = incoming
            incoming_count = 1
        self._merge_from(incoming_state, incoming_count)
        # a merge is a real stream transition: journal it (state + count) so
        # a post-crash restore replays the merged contribution too
        self._journal_record(
            "merge", ({k: incoming_state[k] for k in self._defaults}, incoming_count), {}
        )

    def _merge_from(self, incoming_state: Dict[str, Any], incoming_count: int) -> None:
        prev_count = self._update_count
        self._update_count = prev_count + incoming_count
        current = self._copy_state_dict()
        self._restore_state({k: incoming_state[k] for k in self._defaults})
        # `current` (pre-merge self) carries prev_count updates, the restored
        # incoming state carries incoming_count — weight mean-merges accordingly
        self._reduce_states(current, incoming_weight=prev_count, local_weight=max(incoming_count, 1))
        self._computed = None

    # ---------------------------------------------------------------- reset
    def reset(self) -> None:
        """Reset states to their defaults (reference ``metric.py:673-688``).

        A pending deferred violation (compiled ``validate_args=True`` path)
        still surfaces here, but only *after* the state reset: one ``reset()``
        call both raises the error and leaves a clean metric, instead of
        aborting mid-way and requiring a second call (ADVICE r5).
        """
        pending: Optional[BaseException] = None
        try:
            self._check_pending_violations()
        except RuntimeError as err:  # flags already cleared by the check
            pending = err
        self._update_count = 0
        self._forward_cache = None
        self._computed = None
        for attr in self._defaults:
            self._reset_state_to_default(attr)
        self._cache = None
        self._is_synced = False
        # a mid-stream reset is a state transition like any other: without a
        # journal entry, a post-reset crash would restore (pre-reset snapshot
        # + full journal) and resurrect the accumulation reset() discarded
        self._journal_record("reset", (), {})
        if pending is not None:
            raise pending

    def _reset_state_to_default(self, attr: str) -> None:
        """Rebind one registered state to its default (shared by ``reset``
        and ``load_state_dict(strict="repair")`` so repair can never restore
        a state differently than reset would)."""
        default = self._defaults[attr]
        if isinstance(default, RingBuffer):
            setattr(self, attr, default.copy_empty())
        elif isinstance(default, list):
            setattr(self, attr, [])
        else:
            setattr(self, attr, jnp.array(default))

    def clone(self) -> "Metric":
        """Deep copy of the metric (reference ``metric.py:690-692``)."""
        return deepcopy(self)

    # ----------------------------------------------------------- persistence
    def _copy_state_dict(self) -> Dict[str, Union[Array, List]]:
        cache: Dict[str, Union[Array, List]] = {}
        for attr in self._defaults:
            current = getattr(self, attr)
            if isinstance(current, RingBuffer):
                cache[attr] = current.copy()
            elif isinstance(current, list):
                cache[attr] = [jnp.array(v) for v in current]
            else:
                cache[attr] = jnp.array(current)
        return cache

    def _restore_state(self, cache: Dict[str, Union[Array, List]]) -> None:
        for attr, val in cache.items():
            setattr(self, attr, val)

    def persistent(self, mode: bool = False) -> None:
        """Flip the persistence flag of all states (reference ``metric.py:834-837``)."""
        for key in self._persistent:
            self._persistent[key] = mode

    def state_dict(
        self,
        destination: Optional[Dict] = None,
        prefix: str = "",
        keep_vars: bool = False,
        integrity: bool = False,
        all_states: bool = False,
    ) -> Dict:
        """Serialize persistent states to host numpy (reference ``metric.py:839-871``).

        ``integrity=True`` additionally writes a checksummed, versioned
        metadata block under the non-identifier key ``{prefix}#integrity``
        (see ``torchmetrics_tpu/_resilience/integrity.py``): restores then
        verify per-state checksums and the schema version, rejecting corrupt
        or NaN-poisoned checkpoints instead of silently loading them.

        ``all_states=True`` serializes every registered state regardless of
        its ``persistent`` flag — the contract the snapshot/durability layer
        needs (a preemption must not lose non-persistent accumulators), as
        opposed to the portability contract of ordinary checkpoints.
        """
        destination = {} if destination is None else destination
        for key in self._defaults:
            if not (all_states or self._persistent[key]):
                continue
            current = getattr(self, key)
            if isinstance(current, RingBuffer):
                destination[prefix + key] = np.asarray(current.values())
            elif isinstance(current, list):
                destination[prefix + key] = [np.asarray(v) for v in current]
            else:
                destination[prefix + key] = np.asarray(current)
        if integrity:
            from torchmetrics_tpu._resilience.integrity import attach_integrity

            attach_integrity(destination, list(self._defaults), prefix, type(self).__name__)
        return destination

    def load_state_dict(
        self,
        state_dict: Dict,
        strict: Union[bool, str] = True,
        prefix: str = "",
        _verified: bool = False,
    ) -> None:
        """Restore states from a :meth:`state_dict` mapping (symmetric with its ``prefix``).

        When the checkpoint carries an integrity block (saved with
        ``state_dict(integrity=True)``) every covered state is verified
        before anything loads: checksum mismatches, unknown schema versions,
        and NaN-poisoned payloads raise
        :class:`~torchmetrics_tpu._resilience.errors.StateCorruptionError`
        with the offending state names. ``strict="repair"`` instead resets
        only the corrupted states to their registered defaults, loads the
        rest, and records a ``state_repair`` degradation event (it also
        NaN-screens checkpoints without an integrity block).
        """
        corrupted: Dict[str, str] = {}
        from torchmetrics_tpu._resilience import integrity as _integrity

        meta = state_dict.get(_integrity.integrity_key(prefix))
        if meta is not None and _verified:
            pass  # the caller (MetricCollection's atomic pre-pass) already hashed every state
        elif meta is not None:
            corrupted = _integrity.verify_states(
                state_dict,
                prefix,
                meta,
                type(self).__name__,
                # strict=False tolerates missing keys by contract (filtered/
                # partial checkpoints); present-but-corrupt states still raise
                include_missing=strict is not False,
            )
        elif strict == "repair":
            corrupted = _integrity.screen_nonfinite(state_dict, prefix, list(self._defaults))
        if corrupted and strict != "repair":
            _integrity.raise_corrupted(type(self).__name__, corrupted)
        for key in self._defaults:
            if key in corrupted:
                # repair: only the corrupted state goes back to its default
                self._reset_state_to_default(key)
                continue
            if prefix + key in state_dict:
                val = state_dict[prefix + key]
                if isinstance(self._defaults[key], RingBuffer):
                    rb = self._defaults[key].copy_empty()
                    if isinstance(val, list):
                        for v in val:
                            rb.append(jnp.asarray(v))
                    else:
                        arr = jnp.asarray(val)
                        if arr.size:
                            rb.append(arr)
                    setattr(self, key, rb)
                elif isinstance(val, list):
                    setattr(self, key, [jnp.asarray(v) for v in val])
                elif isinstance(self._defaults[key], list):
                    # a ring-buffer checkpoint (one concatenated array) loaded
                    # into a list-state metric: rewrap so `.append` keeps working
                    arr = jnp.asarray(val)
                    setattr(self, key, [arr] if arr.size else [])
                else:
                    setattr(self, key, jnp.asarray(val))
            elif strict == "repair" and self._persistent[key]:
                # repair semantics must not depend on whether an integrity
                # block survived: a missing persistent state is repaired to
                # its default, same as a block-flagged missing one
                corrupted[key] = "missing from the checkpoint"
                self._reset_state_to_default(key)
            elif strict and self._persistent[key]:
                raise KeyError(f"Missing key {key!r} in state_dict for {self.__class__.__name__}")
        if corrupted:  # strict == "repair"
            self._record_degradation(
                "state_repair",
                detail=(
                    "load_state_dict(strict=\"repair\") reset corrupted state(s) to defaults: "
                    + "; ".join(f"`{k}`: {v}" for k, v in sorted(corrupted.items()))
                ),
            )
            self._computed = None
        # restored dtypes/shapes may differ from what the last handshake saw
        self.__dict__.pop("_handshake_ok_digest", None)
        # a mid-stream manual load is a state transition replay can't
        # reconstruct from update entries: anchor it with a fresh snapshot
        self._journal_record("external", (), {})

    def __getstate__(self) -> Dict[str, Any]:
        """Pickle support: drop wrapped bound methods, numpy-ify arrays (reference ``metric.py:694-702``)."""
        state = {
            k: v
            for k, v in self.__dict__.items()
            if k
            not in (
                "update",
                "compute",
                "_update_signature",
                "_jit_update_fn",
                "_scan_update_fn",
                "_auto_update_fn",
                "_auto_forward_fn",
                "_auto_sigs",
                "_auto_fwd_sigs",
                "_auto_cnt",
                "_ring_count_deltas",
                # a SnapshotManager holds threads + file handles: clones and
                # pickles travel without it (re-attach at the destination)
                "_snapshot_hook",
                # telemetry is per-instance stream history: a pickled/cloned
                # metric is a new stream and re-registers lazily on first use
                "_telem",
                "_obs_seen_sigs",
            )
        }
        for attr in self._defaults:
            cur = state.get(attr)
            if isinstance(cur, RingBuffer):
                pass  # RingBuffer pickles itself (numpy-ifies its arrays)
            elif isinstance(cur, list):
                state[attr] = [np.asarray(v) for v in cur]
            elif cur is not None:
                state[attr] = np.asarray(cur)
        for key in ("_defaults", "_cache"):
            block = state.get(key)
            if isinstance(block, dict):
                state[key] = {
                    k: (
                        v
                        if isinstance(v, RingBuffer)
                        else [np.asarray(x) for x in v] if isinstance(v, list) else np.asarray(v)
                    )
                    for k, v in block.items()
                }
        state["_computed"] = None
        return state

    def __setstate__(self, state: Dict[str, Any]) -> None:
        """Unpickle: re-wrap update/compute (reference ``metric.py:704-713``)."""
        self.__dict__.update(state)
        for attr in self._defaults:
            cur = getattr(self, attr, None)
            if isinstance(cur, RingBuffer):
                pass  # already rehydrated by RingBuffer.__setstate__
            elif isinstance(cur, list):
                setattr(self, attr, [jnp.asarray(v) for v in cur])
            elif cur is not None:
                setattr(self, attr, jnp.asarray(cur))
        self._update_signature = inspect.signature(self.update)
        self.update = self._wrap_update(self.update)  # type: ignore[method-assign]
        self.compute = self._wrap_compute(self.compute)  # type: ignore[method-assign]
        self._auto_sigs = {}
        self._auto_fwd_sigs = {}
        self._auto_names = None
        # pickles written before the resilience subsystem lack these knobs
        self.__dict__.setdefault("sync_policy", None)
        self.__dict__.setdefault("nan_policy", None)
        self.__dict__.setdefault("_sync_policy_explicit", False)
        self.__dict__.setdefault("_resilience_events", [])
        self.__dict__.setdefault("_quarantined_updates", 0)
        self.__dict__.setdefault("_snapshot_hook", None)
        # pickles written before severity-carrying traced validators
        self.__dict__.setdefault("_viol_sevs", None)

    def __setattr__(self, name: str, value: Any) -> None:
        """Class-flag immutability guard (reference ``metric.py:715-726``)."""
        if name in ("higher_is_better", "is_differentiable", "full_state_update"):
            raise RuntimeError(f"Can't change const `{name}`.")
        object.__setattr__(self, name, value)

    # ---------------------------------------------------------- device/dtype
    def to_device(self, device: Any) -> "Metric":
        """Move all states to ``device`` (a ``jax.Device`` or sharding)."""
        for attr in self._defaults:
            current = getattr(self, attr)
            if isinstance(current, RingBuffer):
                current.to_device(device)
            elif isinstance(current, list):
                setattr(self, attr, [jax.device_put(v, device) for v in current])
            else:
                setattr(self, attr, jax.device_put(current, device))
        return self

    def set_dtype(self, dst_type: Any) -> "Metric":
        """Cast floating states to ``dst_type`` (reference ``metric.py:770-780``)."""
        self._dtype_policy = dst_type
        # state dtypes are part of the cross-process structure contract: the
        # next guarded sync must re-run the handshake
        self.__dict__.pop("_handshake_ok_digest", None)
        for attr in self._defaults:
            current = getattr(self, attr)
            if isinstance(current, RingBuffer):
                if current.data is not None and jnp.issubdtype(current.data.dtype, jnp.floating):
                    current.data = current.data.astype(dst_type)
            elif isinstance(current, list):
                setattr(
                    self,
                    attr,
                    [v.astype(dst_type) if jnp.issubdtype(v.dtype, jnp.floating) else v for v in current],
                )
            elif jnp.issubdtype(current.dtype, jnp.floating):
                setattr(self, attr, current.astype(dst_type))
        return self

    @property
    def device(self) -> Any:
        """Device of the metric's states (reference ``metric.py:729-731``).

        JAX arrays carry their own placement, so this reports where the first
        array state lives (the default device before any state exists).
        """
        for attr in self._defaults:
            current = getattr(self, attr, None)
            if isinstance(current, jax.Array):
                return list(current.devices())[0]
            if isinstance(current, list) and current and isinstance(current[0], jax.Array):
                return list(current[0].devices())[0]
        return jax.devices()[0]

    @property
    def dtype(self) -> Any:
        """Default floating dtype of the metric (reference ``metric.py:734-736``)."""
        if self._dtype_policy is not None:
            return jnp.dtype(self._dtype_policy)
        for attr in self._defaults:
            current = getattr(self, attr, None)
            if isinstance(current, jax.Array) and jnp.issubdtype(current.dtype, jnp.floating):
                return current.dtype
        return jnp.dtype(jnp.float32)

    def type(self, dst_type: Any) -> "Metric":  # noqa: A003 - parity no-op (reference metric.py:738-744)
        return self

    def float(self) -> "Metric":  # noqa: A003 - parity no-op (reference metric.py:746-768)
        return self

    def double(self) -> "Metric":
        return self

    def half(self) -> "Metric":
        return self

    # ---------------------------------------------------------------- dunder
    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        """Filter kwargs to those accepted by this metric's update (reference ``metric.py:892-911``)."""
        _params = (inspect.Parameter.VAR_POSITIONAL, inspect.Parameter.VAR_KEYWORD)
        _sign_params = self._update_signature.parameters
        filtered_kwargs = {
            k: v for k, v in kwargs.items() if (k in _sign_params and _sign_params[k].kind not in _params)
        }
        exists_var_keyword = any(v.kind == inspect.Parameter.VAR_KEYWORD for v in _sign_params.values())
        return kwargs if exists_var_keyword else filtered_kwargs

    def __hash__(self) -> int:
        """Id+state hash (reference ``metric.py:913-936``)."""
        hash_vals = [self.__class__.__name__, id(self)]
        for key in self._defaults:
            val = getattr(self, key)
            if isinstance(val, list):
                hash_vals.extend(id(v) for v in val)
            else:
                hash_vals.append(id(val))
        return hash(tuple(hash_vals))

    def __repr__(self) -> str:
        return f"{self.__class__.__name__}()"

    def __iter__(self):
        raise NotImplementedError("Metrics does not support iteration.")

    def __abs__(self) -> "CompositionalMetric":
        return CompositionalMetric(jnp.abs, self, None)

    def __add__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.add, self, other)

    def __and__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_and, self, other)

    def __eq__(self, other: Any) -> "CompositionalMetric":  # type: ignore[override]
        return CompositionalMetric(jnp.equal, self, other)

    def __floordiv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.floor_divide, self, other)

    def __ge__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.greater_equal, self, other)

    def __gt__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.greater, self, other)

    def __invert__(self) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_not, self, None)

    def __le__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.less_equal, self, other)

    def __lt__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.less, self, other)

    def __matmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.matmul, self, other)

    def __mod__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.mod, self, other)

    def __mul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.multiply, self, other)

    def __ne__(self, other: Any) -> "CompositionalMetric":  # type: ignore[override]
        return CompositionalMetric(jnp.not_equal, self, other)

    def __neg__(self) -> "CompositionalMetric":
        return CompositionalMetric(_neg, self, None)

    def __or__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_or, self, other)

    def __pos__(self) -> "CompositionalMetric":
        return CompositionalMetric(jnp.abs, self, None)

    def __pow__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.power, self, other)

    def __radd__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.add, other, self)

    def __rand__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(lambda a, b: jnp.bitwise_and(b, a), self, other)

    def __rfloordiv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.floor_divide, other, self)

    def __rmatmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.matmul, other, self)

    def __rmod__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.mod, other, self)

    def __rmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.multiply, other, self)

    def __ror__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(lambda a, b: jnp.bitwise_or(b, a), self, other)

    def __rpow__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.power, other, self)

    def __rsub__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.subtract, other, self)

    def __rtruediv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.true_divide, other, self)

    def __rxor__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(lambda a, b: jnp.bitwise_xor(b, a), self, other)

    def __sub__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.subtract, self, other)

    def __truediv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.true_divide, self, other)

    def __xor__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_xor, self, other)

    def __getitem__(self, idx: int) -> "CompositionalMetric":
        return CompositionalMetric(lambda x: x[idx], self, None)

    # ------------------------------------------------------------------ plot
    def _plot(self, val: Optional[Any] = None, ax: Optional[Any] = None):
        from torchmetrics_tpu.utilities.plot import plot_single_or_multi_val

        val = val if val is not None else self.compute()
        return plot_single_or_multi_val(
            val,
            ax=ax,
            higher_is_better=self.higher_is_better,
            lower_bound=self.plot_lower_bound,
            upper_bound=self.plot_upper_bound,
            legend_name=self.plot_legend_name,
            name=self.__class__.__name__,
        )

    def plot(self, *args: Any, **kwargs: Any):
        """Plot the (current or provided) metric value."""
        return self._plot(*args, **kwargs)


def _neg(x: Array) -> Array:
    return -jnp.abs(x)


class CompositionalMetric(Metric):
    """Lazy composition of metrics under an elementwise op (reference ``metric.py:1088-1211``)."""

    full_state_update = True

    def _wrap_compute(self, compute: Callable) -> Callable:
        # no caching/sync wrapping: children compute (and sync) themselves, and
        # their states keep changing between our compute() calls (reference
        # metric.py:1209-1211 returns compute unwrapped for CompositionalMetric)
        return compute

    def __init__(self, operator: Callable, metric_a: Union[Metric, float, Array], metric_b: Union[Metric, float, Array, None]) -> None:
        super().__init__()
        self.op = operator
        self.metric_a = metric_a if isinstance(metric_a, Metric) else (jnp.asarray(metric_a) if metric_a is not None else None)
        self.metric_b = metric_b if isinstance(metric_b, Metric) else (jnp.asarray(metric_b) if metric_b is not None else None)

    def _sync_dist(self, dist_sync_fn: Optional[Callable] = None, process_group: Optional[Any] = None) -> None:
        pass  # children sync themselves

    def update(self, *args: Any, **kwargs: Any) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.update(*args, **self.metric_a._filter_kwargs(**kwargs))
        if isinstance(self.metric_b, Metric):
            self.metric_b.update(*args, **self.metric_b._filter_kwargs(**kwargs))

    def compute(self) -> Any:
        val_a = self.metric_a.compute() if isinstance(self.metric_a, Metric) else self.metric_a
        val_b = self.metric_b.compute() if isinstance(self.metric_b, Metric) else self.metric_b
        if val_b is None:
            return self.op(val_a)
        return self.op(val_a, val_b)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        val_a = (
            self.metric_a(*args, **self.metric_a._filter_kwargs(**kwargs))
            if isinstance(self.metric_a, Metric)
            else self.metric_a
        )
        val_b = (
            self.metric_b(*args, **self.metric_b._filter_kwargs(**kwargs))
            if isinstance(self.metric_b, Metric)
            else self.metric_b
        )
        if val_a is None:
            self._forward_cache = None
        elif val_b is None:
            if isinstance(self.metric_b, Metric):
                self._forward_cache = None
            else:
                self._forward_cache = self.op(val_a)
        else:
            self._forward_cache = self.op(val_a, val_b)
        return self._forward_cache

    def reset(self) -> None:
        # reset BOTH children even when one surfaces a pending deferred
        # violation from its own reset (clear-then-raise contract)
        pending: Optional[BaseException] = None
        for child in (self.metric_a, self.metric_b):
            if isinstance(child, Metric):
                try:
                    child.reset()
                except RuntimeError as err:
                    pending = pending or err
        if pending is not None:
            raise pending

    def persistent(self, mode: bool = False) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.persistent(mode=mode)
        if isinstance(self.metric_b, Metric):
            self.metric_b.persistent(mode=mode)

    def __repr__(self) -> str:
        _op_metrics = f"(\n  {self.op.__name__ if hasattr(self.op, '__name__') else self.op}(\n    {self.metric_a!r},\n    {self.metric_b!r}\n  )\n)"
        return self.__class__.__name__ + _op_metrics

    def __hash__(self) -> int:
        return object.__hash__(self)
