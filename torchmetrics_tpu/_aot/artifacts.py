"""AOT artifact (de)serialization: compiled XLA executables on disk.

Two artifact formats, negotiated at build time and recorded in the header:

- ``xla_exec`` (primary): ``jax.experimental.serialize_executable`` round-trip
  of the *compiled* executable. Loading skips BOTH Python tracing and XLA
  compilation — a deployed replica pays only deserialization. The payload is
  backend- and version-specific, which is exactly why every artifact is keyed
  by :func:`backend_fingerprint` and verified before loading.
- ``stablehlo`` (fallback): ``jax.export`` StableHLO serialization for
  backends where the executable round-trip is unsupported. Loading skips
  Python tracing of the original update body but re-runs XLA compilation on
  first call (a partial cold-start win, recorded distinctly in telemetry).

Any failure at any stage is reported to the caller as ``None`` — the cache
layer falls back to ordinary tracing, never to wrong results.
"""

from __future__ import annotations

import pickle
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.export as _jax_export

try:  # the executable round-trip is experimental; absence selects stablehlo
    from jax.experimental import serialize_executable as _se
except Exception:  # pragma: no cover - depends on the installed jax build
    _se = None

__all__ = [
    "backend_fingerprint",
    "build_artifact",
    "load_artifact",
    "FORMAT_XLA_EXEC",
    "FORMAT_STABLEHLO",
]

FORMAT_XLA_EXEC = "xla_exec"
FORMAT_STABLEHLO = "stablehlo"

_FINGERPRINT: Optional[Dict[str, str]] = None


def backend_fingerprint() -> Dict[str, str]:
    """Stable identity of the runtime a serialized executable is valid for.

    A compiled XLA executable is specific to the jax/jaxlib pair, the backend
    platform, the device kind, and the addressable device count (SPMD steps
    bake the mesh in). Any component differing between writer and loader
    makes the artifact unloadable-by-policy: the cache treats it as a miss.
    """
    global _FINGERPRINT
    if _FINGERPRINT is None:
        import jaxlib

        devices = jax.devices()
        try:
            # explicit import: `jax.extend` is lazy — reading it off the
            # `jax` module only works if something else imported it first,
            # which made the fingerprint depend on process import order
            # (writer said 'cpu', a fresh CLI process said '?', and every
            # artifact went permanently stale)
            from jax.extend import backend as _jex_backend

            platform_version = _jex_backend.get_backend().platform_version
        except Exception:  # pragma: no cover - very old backends
            platform_version = "?"
        _FINGERPRINT = {
            "jax": jax.__version__,
            "jaxlib": getattr(jaxlib, "__version__", "?"),
            "platform": devices[0].platform,
            "device_kind": devices[0].device_kind,
            "device_count": str(len(devices)),
            "platform_version": str(platform_version),
        }
    return dict(_FINGERPRINT)


def build_artifact(
    jit_fn: Callable, args: tuple, avoid_format: Optional[str] = None, want_payload: bool = True
) -> Tuple[Optional[Callable], Optional[str], Optional[bytes]]:
    """Lower+compile ``jit_fn`` for ``args`` and serialize the result.

    Returns ``(compiled_callable, fmt, payload)``. The compiled callable is
    always usable in-process when lowering succeeded; ``fmt``/``payload`` are
    ``None`` when neither serialization format worked (the executable still
    serves this process, it just cannot be cached). Lowering itself failing
    returns ``(None, None, None)`` — the caller falls back to the plain
    jitted path.

    ``avoid_format`` is the cache's self-healing hook: some CPU executables
    serialize fine but reference process-local JIT symbols, so deserialization
    only fails in a FRESH process — undetectable at build time. When a loaded
    artifact's payload failed to deserialize, the caller rebuilds with that
    format excluded so the re-stored artifact actually loads next time.
    """
    try:
        compiled = jit_fn.lower(*args).compile()
    except Exception:
        return None, None, None
    if not want_payload:
        # memory-only warm (no cache directory): the serialized payload
        # would be built and immediately discarded — skip the pickle/export
        return compiled, None, None
    if _se is not None and avoid_format != FORMAT_XLA_EXEC:
        try:
            payload = pickle.dumps(_se.serialize(compiled), protocol=pickle.HIGHEST_PROTOCOL)
            return compiled, FORMAT_XLA_EXEC, payload
        except Exception:
            pass  # backend without executable round-trip: try StableHLO
    try:
        exported = _jax_export.export(jit_fn)(*args)
        return compiled, FORMAT_STABLEHLO, bytes(exported.serialize())
    except Exception:
        return compiled, None, None


def load_artifact(fmt: str, payload: bytes) -> Optional[Callable]:
    """Rehydrate a serialized executable; ``None`` on any failure.

    ``xla_exec`` payloads load straight into a ready executable.
    ``stablehlo`` payloads come back as a jitted call into the deserialized
    StableHLO module — tracing is skipped, XLA compilation happens lazily on
    the first invocation.
    """
    try:
        if fmt == FORMAT_XLA_EXEC:
            if _se is None:
                return None
            serialized, in_tree, out_tree = pickle.loads(payload)
            return _se.deserialize_and_load(serialized, in_tree, out_tree)
        if fmt == FORMAT_STABLEHLO:
            exported = _jax_export.deserialize(bytearray(payload))
            return jax.jit(exported.call)
    except Exception:
        return None
    return None


def executable_roundtrip_supported() -> bool:
    """True when the primary (trace+compile-free) format is available."""
    return _se is not None
