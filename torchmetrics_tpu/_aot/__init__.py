"""Ahead-of-time executable serialization + warm persistent compile cache.

Kills fleet cold-start: with ``TM_TPU_AOT_CACHE`` (or
:func:`set_aot_cache`) pointing at a directory, every hot-path executable
the runtime builds — the certified default update path, ``jit_update``/
``scan_update``, the SPMD engine's donated fused step, StreamPool's vmapped
stream step — is serialized after its first compile and loaded (no trace,
no XLA compile) by every later process. See ``cache.py`` for the artifact
format and the fallback ladder; ``default_path.py`` for the certified
default-path sweep the golden recompile manifest locks down.

This ``__init__`` stays import-light: ``metric.py`` pulls the switch from
``state`` at module scope, everything heavier loads lazily on first use.
"""

from __future__ import annotations

from typing import Any

from torchmetrics_tpu._aot.state import AOT, get_aot_cache, set_aot_cache

__all__ = [
    "AOT",
    "set_aot_cache",
    "get_aot_cache",
    "aot_stats",
    "reset_aot_stats",
    "get_cache",
    "wrap_executable",
]

_LAZY = {"aot_stats", "reset_aot_stats", "get_cache", "wrap_executable"}


def __getattr__(name: str) -> Any:
    if name in _LAZY:
        from torchmetrics_tpu._aot import cache as _cache

        return getattr(_cache, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
