"""The certified default path: the canonical out-of-the-box compile sweep.

One deterministic definition of "the certified default path" shared by three
consumers so they can never drift apart:

- ``tools/compile_golden.py`` writes the golden compile-count manifest
  (``_analysis/compile_golden.json``) from this sweep;
- the tier-1 recompile gate (``tests/unittests/analysis/test_recompile_gate.py``)
  re-drives it and fails when a PR introduces ANY compile beyond the
  manifest, with the churn detector naming the differing cache-key
  component(s);
- ``bench.py``'s cold-start section precompiles exactly these classes in
  fresh subprocesses to measure ``cold_start_ms`` / ``aot_warm_vs_cold_speedup``.

Every case constructs at ctor defaults (``validate_args=True`` wherever the
knob exists) and feeds a fixed-seed canonical batch, so the observed compile
cache keys — argument structure, static values, shapes, dtypes, dtype
policy — are bit-stable across processes and machines.
"""

from __future__ import annotations

import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["DEFAULT_PATH_CASES", "canonical_batch", "drive_default_path", "collect_compile_keys"]

_SEED = 1234
_N = 32


def _data(maker: str) -> Tuple[Any, ...]:
    import jax.numpy as jnp

    rng = np.random.default_rng(_SEED)
    if maker == "bin":
        return (jnp.asarray(rng.random(_N).astype(np.float32)), jnp.asarray(rng.integers(0, 2, _N)))
    if maker == "mc":
        p = rng.random((_N, 4)).astype(np.float32)
        return (jnp.asarray(p / p.sum(1, keepdims=True)), jnp.asarray(rng.integers(0, 4, _N)))
    if maker == "ml":
        return (
            jnp.asarray(rng.random((_N, 3)).astype(np.float32)),
            jnp.asarray(rng.integers(0, 2, (_N, 3))),
        )
    if maker == "reg":
        return (
            jnp.asarray(rng.standard_normal(_N).astype(np.float32)),
            jnp.asarray(rng.standard_normal(_N).astype(np.float32)),
        )
    if maker == "reg_pos":
        return (
            jnp.asarray((rng.random(_N) + 0.1).astype(np.float32)),
            jnp.asarray((rng.random(_N) + 0.1).astype(np.float32)),
        )
    if maker == "probs2d":
        p = rng.random((_N, 5)).astype(np.float32)
        q = rng.random((_N, 5)).astype(np.float32)
        return (jnp.asarray(p / p.sum(1, keepdims=True)), jnp.asarray(q / q.sum(1, keepdims=True)))
    if maker == "agg":
        return (jnp.asarray(rng.random(_N).astype(np.float32)),)
    raise ValueError(f"unknown canonical batch maker {maker!r}")


def canonical_batch(name: str) -> Tuple[Any, ...]:
    """The fixed-seed batch the certified sweep feeds class ``name``."""
    return _data(DEFAULT_PATH_CASES[name][1])


def _cases() -> Dict[str, Tuple[Callable[[], Any], str]]:
    import torchmetrics_tpu as tm
    from torchmetrics_tpu import aggregation

    # a representative cross-family slice of the verdict-(a)/(b) catalog —
    # bounded (the gate re-drives this inside the tier-1 budget) but wide
    # enough that a recompile regression in any family trips it
    return {
        "MeanMetric": (lambda: aggregation.MeanMetric(), "agg"),
        "MaxMetric": (lambda: aggregation.MaxMetric(), "agg"),
        "BinaryStatScores": (lambda: tm.BinaryStatScores(), "bin"),
        "BinaryAccuracy": (lambda: tm.BinaryAccuracy(), "bin"),
        "BinaryF1Score": (lambda: tm.BinaryF1Score(), "bin"),
        "BinaryConfusionMatrix": (lambda: tm.BinaryConfusionMatrix(), "bin"),
        "MulticlassAccuracy": (lambda: tm.MulticlassAccuracy(num_classes=4), "mc"),
        "MulticlassStatScores": (lambda: tm.MulticlassStatScores(num_classes=4), "mc"),
        "MultilabelAccuracy": (lambda: tm.MultilabelAccuracy(num_labels=3), "ml"),
        "MultilabelRankingLoss": (lambda: tm.MultilabelRankingLoss(num_labels=3), "ml"),
        "MeanSquaredError": (lambda: tm.MeanSquaredError(), "reg"),
        "MeanAbsoluteError": (lambda: tm.MeanAbsoluteError(), "reg"),
        "R2Score": (lambda: tm.R2Score(), "reg"),
        "PearsonCorrCoef": (lambda: tm.PearsonCorrCoef(), "reg"),
        "KLDivergence": (lambda: tm.KLDivergence(), "probs2d"),
        "TweedieDevianceScore": (lambda: tm.TweedieDevianceScore(), "reg_pos"),
    }


class _LazyCases(dict):
    """Defer the metric-class imports until the sweep is actually used."""

    def _fill(self) -> None:
        if not dict.__len__(self):
            dict.update(self, _cases())

    def __getitem__(self, key):  # noqa: D105
        self._fill()
        return super().__getitem__(key)

    def __iter__(self):  # noqa: D105
        self._fill()
        return super().__iter__()

    def __len__(self):  # noqa: D105
        self._fill()
        return super().__len__()

    def keys(self):  # noqa: D102
        self._fill()
        return super().keys()

    def items(self):  # noqa: D102
        self._fill()
        return super().items()


DEFAULT_PATH_CASES: Dict[str, Tuple[Callable[[], Any], str]] = _LazyCases()


def collect_compile_keys(metric: Any) -> List[Dict[str, Any]]:
    """Every distinct compiled-path cache key this instance reported,
    straight from the recompile-churn detector's store."""
    telem = metric.__dict__.get("_telem")
    if telem is None:
        return []
    out = []
    for kind, components in sorted(telem._compile_keys):
        out.append({"kind": kind, "components": dict(components)})
    return out


def drive_default_path(
    names: Optional[List[str]] = None,
    updates: int = 3,
    precompile: bool = False,
) -> Dict[str, List[Dict[str, Any]]]:
    """Drive the certified default path; return per-class compile keys.

    Telemetry is forced on for the drive (the churn detector is the
    measurement instrument) and restored afterwards. Each class gets a fresh
    instance, ``updates`` repeat-signature update calls (first eager +
    signature registration, later ones compiled), and one ``compute()``.
    With ``precompile=True`` the sweep warms through ``Metric.precompile``
    first — the deployment flow the AOT cache accelerates.
    """
    from torchmetrics_tpu._observability.state import OBS

    cases = DEFAULT_PATH_CASES
    names = list(names) if names is not None else sorted(cases.keys())
    was_enabled = OBS.enabled
    OBS.enabled = True
    observed: Dict[str, List[Dict[str, Any]]] = {}
    try:
        for name in names:
            ctor, _maker = cases[name]
            metric = ctor()
            args = canonical_batch(name)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                if precompile:
                    metric.precompile(*args)
                for _ in range(updates):
                    metric.update(*args)
                metric.compute()
            observed[name] = collect_compile_keys(metric)
    finally:
        OBS.enabled = was_enabled
    return observed
