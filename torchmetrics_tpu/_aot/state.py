"""Process-wide AOT-cache switch — the ONE object compile seams may touch.

Mirrors the telemetry kill-switch contract (``_observability/state.py``):
every executable-construction site guards itself with::

    if _AOT.active:
        ...wrap the fresh jitted callable in the AOT dispatcher...

where ``_AOT`` is this module's :data:`AOT` singleton. ``active`` lives in a
``__slots__`` slot, so the disabled path costs one attribute load and one
branch — and it is only ever paid when a NEW executable is built (never per
update call), so with the cache unset the runtime is instruction-identical
to a build without the AOT machinery on every hot path.

Switches:

- env ``TM_TPU_AOT_CACHE=/path`` points the persistent on-disk executable
  cache at a directory (read at import);
- :func:`set_aot_cache` re-points or disables it at runtime.

This module must stay import-light (no jax, no numpy): ``metric.py`` imports
it at module scope.
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["AOT", "set_aot_cache", "get_aot_cache"]


class _AotState:
    """Mutable singleton holding the global AOT-cache switch.

    ``__slots__`` keeps the ``active`` read a plain slot load and makes
    accidental attribute growth an error.
    """

    __slots__ = ("active", "cache_dir")

    def __init__(self) -> None:
        path = os.environ.get("TM_TPU_AOT_CACHE", "").strip()
        self.cache_dir: Optional[str] = path or None
        self.active = bool(path)


AOT = _AotState()

_XLA_CACHE_ARMED = False
_XLA_SAVED: Optional[tuple] = None  # (min_compile_time_secs, min_entry_size_bytes) pre-arm
_XLA_WROTE: Optional[str] = None  # the exact dir this module set, so disarm never clobbers a user's


def _arm_xla_cache(directory: str) -> None:
    """Layer 2: point JAX's own persistent compilation cache under the dir.

    The artifact store (layer 1) serializes the hot-path executables the
    dispatcher routes; everything it cannot route — auxiliary jitted helpers,
    the per-primitive compiles behind eager ``compute`` — still re-compiles
    per process. JAX's persistent cache at ``<dir>/xla`` catches those (and
    is the whole-cache fallback on backends without an executable
    round-trip). Thresholds drop to zero because fleet cold-start is paid in
    thousands of sub-second compiles, exactly the ones the defaults skip.
    A user-configured ``jax_compilation_cache_dir`` always wins.
    """
    global _XLA_CACHE_ARMED, _XLA_SAVED, _XLA_WROTE
    try:
        import jax

        current = jax.config.jax_compilation_cache_dir
        if current is None or (_XLA_CACHE_ARMED and current == _XLA_WROTE):
            if not _XLA_CACHE_ARMED:
                _XLA_SAVED = (
                    jax.config.jax_persistent_cache_min_compile_time_secs,
                    jax.config.jax_persistent_cache_min_entry_size_bytes,
                )
            _XLA_WROTE = os.path.join(directory, "xla")
            jax.config.update("jax_compilation_cache_dir", _XLA_WROTE)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
            _XLA_CACHE_ARMED = True
    except Exception:  # noqa: BLE001 - older jax without the knobs: layer 1 still works
        pass


def _disarm_xla_cache() -> None:
    global _XLA_CACHE_ARMED, _XLA_SAVED, _XLA_WROTE
    if not _XLA_CACHE_ARMED:
        return
    try:
        import jax

        if jax.config.jax_compilation_cache_dir == _XLA_WROTE:
            # only unwind OUR configuration: a dir the user pointed jax at
            # after we armed (and the thresholds they now rely on) stays
            jax.config.update("jax_compilation_cache_dir", None)
            if _XLA_SAVED is not None:
                # restore the pre-arm thresholds: leaving them zeroed would
                # make a user's OWN later cache dir persist every sub-second
                # compile
                jax.config.update("jax_persistent_cache_min_compile_time_secs", _XLA_SAVED[0])
                jax.config.update("jax_persistent_cache_min_entry_size_bytes", _XLA_SAVED[1])
    except Exception:  # noqa: BLE001
        pass
    _XLA_CACHE_ARMED = False
    _XLA_SAVED = None
    _XLA_WROTE = None


def ensure_xla_cache() -> None:
    """Arm layer 2 for the env-var path (``TM_TPU_AOT_CACHE`` read at import).

    ``metric.py`` calls this once at module scope — jax is already imported
    there, so this module's own import stays jax-free for the CLI tools.
    """
    if AOT.active and AOT.cache_dir:
        _arm_xla_cache(AOT.cache_dir)


def set_aot_cache(directory: Optional[str]) -> None:
    """Point the persistent AOT executable cache at ``directory``.

    ``None`` (or ``""``) disables the disk cache: already-wrapped executables
    keep their in-memory entries but stop touching disk, and newly built
    executables skip the AOT machinery entirely. The directory is created
    lazily on the first artifact write; an unwritable directory degrades to
    tracing with an ``aot_cache_unwritable`` bus event — it never raises on
    the update path. Pointing at a directory also arms JAX's persistent
    compilation cache under ``<dir>/xla`` (see :func:`_arm_xla_cache`);
    disabling disarms it unless the user configured their own.
    """
    path = (directory or "").strip() if isinstance(directory, str) or directory is None else str(directory)
    AOT.cache_dir = path or None
    AOT.active = bool(path)
    if AOT.active:
        _arm_xla_cache(path)
    else:
        _disarm_xla_cache()


def get_aot_cache() -> Optional[str]:
    """The current AOT cache directory (``None`` when the cache is off)."""
    return AOT.cache_dir
