"""Warm persistent on-disk cache for compiled metric executables.

Every hot-path executable the runtime builds — ``Metric``'s auto
update/forward, ``jit_update``/``scan_update``, the SPMD engine's donated
fused step, StreamPool's vmapped stream step — goes through one seam: a
fresh ``jax.jit`` callable is produced and cached under a structural key.
With an AOT cache directory set (``TM_TPU_AOT_CACHE`` /
:func:`~torchmetrics_tpu._aot.state.set_aot_cache`), that seam wraps the
callable in an :class:`_AotDispatch`: per concrete argument-signature the
dispatcher loads a serialized executable from disk (skipping trace+compile
entirely) or, on a miss, lowers+compiles once and writes the artifact for
the next process.

Artifact layout (one file per executable, ``<kind>.<digest>.aot``)::

    TMAOT1\\n                       magic
    <8-byte LE header length>
    <header json>                  key components, fingerprint, format,
                                   payload sha256, sizes, created timestamp
    <payload>                      serialized executable (artifacts.py)

Writes are atomic (same-directory temp file -> flush -> fsync -> rename ->
directory fsync, the snapshot-store idiom) and loads verify the magic, the
payload checksum, the cache-key digest, and the backend fingerprint before
deserializing. Any mismatch or corruption falls back silently to tracing —
never to wrong results — counted as ``aot_cache|result=fallback`` with an
``aot_fallback`` bus event. An unwritable cache directory degrades the same
way (``aot_cache_unwritable`` event, never an exception on the update path).

Trust model: artifacts deserialize via pickle (the executable round-trip's
own wire format), so the cache directory must be operator-controlled — the
checksummed header defends against corruption, not tampering.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

from torchmetrics_tpu._analysis.locksan import SAN as _SAN
from torchmetrics_tpu._analysis.locksan import check_access as _san_check
from torchmetrics_tpu._analysis.locksan import new_lock as _san_lock
from torchmetrics_tpu._aot import artifacts as _artifacts
from torchmetrics_tpu._aot.state import AOT
from torchmetrics_tpu._observability import costs as _costs
from torchmetrics_tpu._observability import tracing as _obs_trace
from torchmetrics_tpu._observability.events import BUS as _BUS
from torchmetrics_tpu._observability.state import OBS as _OBS

__all__ = [
    "AotCache",
    "get_cache",
    "wrap_executable",
    "aot_stats",
    "reset_aot_stats",
]

_MAGIC = b"TMAOT1\n"
_HEADER_LEN = struct.Struct("<Q")
_HEADER_VERSION = 1
_SUFFIX = ".aot"


def _fsync_dir(directory: Path) -> None:
    """Best-effort directory fsync so a rename survives a machine crash."""
    try:
        fd = os.open(str(directory), os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _cost_from_header(header: Optional[Dict[str, Any]]) -> Optional[Any]:
    """Rebuild the stored cost claim from an artifact header, if present."""
    if not header:
        return None
    try:
        flops = float(header.get("cost_flops", 0.0) or 0.0)
        bytes_accessed = float(header.get("cost_bytes_accessed", 0.0) or 0.0)
    except (TypeError, ValueError):
        return None
    if flops <= 0.0 and bytes_accessed <= 0.0:
        return None
    return _costs.ExecutableCost(flops=flops, bytes_accessed=bytes_accessed)


def _aval_signature(args: tuple) -> Tuple[str, Tuple[Any, ...]]:
    """Hashable per-call signature: tree structure + every leaf's aval.

    ``shaped_abstractify`` captures shape, dtype AND weak-type — a serialized
    executable only replays calls whose avals match exactly, so the
    dispatcher must key at the same granularity XLA validates at.
    """
    from jax.api_util import shaped_abstractify

    leaves, treedef = jax.tree_util.tree_flatten(args)
    return str(treedef), tuple(shaped_abstractify(leaf) for leaf in leaves)


def _digest(owner: str, kind: str, key_repr: str, call_sig: Tuple[str, Tuple[Any, ...]]) -> str:
    """Stable cross-process cache key: sha256 of the full component record.

    The components are exactly the ones the recompile-churn detector diffs
    (argument structure, static values, shapes/dtypes, dtype policy — all
    folded into ``key_repr`` + the call avals) plus the owner class, the
    executable kind, and the backend fingerprint.
    """
    record = json.dumps(
        {
            "v": _HEADER_VERSION,
            "owner": owner,
            "kind": kind,
            "key": key_repr,
            "call_tree": call_sig[0],
            "call_avals": [str(a) for a in call_sig[1]],
            # the backend fingerprint is deliberately NOT part of the key: a
            # jax upgrade must find the OLD artifact and refuse it loudly
            # (named fallback + re-write), not silently miss beside it
        },
        sort_keys=True,
    )
    return hashlib.sha256(record.encode("utf-8")).hexdigest()


class AotCache:  # concurrency: shared hot paths bump stats while benches/tests scrape
    """One on-disk artifact store (list/load/store/verify/evict).

    Disk operations never run under the lock — the lock only guards the
    host-side stats counters (scraped by benches and tests while hot paths
    record). Concurrent writers of the same artifact are safe by
    construction: both produce identical bytes and the atomic rename makes
    one of them win.
    """

    def __init__(self, directory: str) -> None:
        self.directory = Path(directory)
        self._lock = _san_lock("AotCache._lock")
        # concurrency: shared stats dict guarded-by _lock
        self._stats: Dict[str, int] = {
            "hits": 0, "misses": 0, "fallbacks": 0, "writes": 0, "write_errors": 0,
        }

    # --------------------------------------------------------------- counters
    def _bump(self, key: str, telem_obj: Any = None, label: Optional[str] = None) -> None:
        with self._lock:
            if _SAN.enabled:
                _san_check(self, "_stats")
            self._stats[key] = self._stats.get(key, 0) + 1
        if telem_obj is not None and _OBS.enabled and label is not None:
            from torchmetrics_tpu._observability.telemetry import telemetry_for

            telemetry_for(telem_obj).inc(f"aot_cache|result={label}")

    def stats(self) -> Dict[str, int]:
        with self._lock:
            if _SAN.enabled:
                _san_check(self, "_stats")
            return dict(self._stats)

    # ------------------------------------------------------------------ paths
    def artifact_path(self, kind: str, digest: str) -> Path:
        return self.directory / f"{kind}.{digest[:24]}{_SUFFIX}"

    # ------------------------------------------------------------------- load
    def load(
        self, kind: str, digest: str
    ) -> Tuple[Optional[Callable], Optional[str], Optional[str], Optional[Dict]]:
        """Rehydrate one artifact: ``(callable, None, fmt, header)`` on a
        verified hit, ``(None, None, None, None)`` on a clean miss (no
        artifact), ``(None, reason, fmt-or-None, header-or-None)`` when an
        artifact exists but cannot be trusted or loaded — ``fmt`` names the
        stored format so the caller can rebuild around a format whose
        payloads fail to deserialize on this runtime (see
        ``build_artifact(avoid_format=...)``). The header rides along so a
        disk hit recovers compile-time metadata (the profiling layer's
        ``cost_flops``/``cost_bytes_accessed``) without re-lowering."""
        path = self.artifact_path(kind, digest)
        try:
            raw = path.read_bytes()
        except FileNotFoundError:
            return None, None, None, None
        except OSError as err:
            return None, f"unreadable artifact: {type(err).__name__}", None, None
        header, payload, reason = self._parse(raw, digest)
        if header is None:
            return None, reason, None, None
        fn = _artifacts.load_artifact(header["format"], payload)
        if fn is None:
            return None, f"deserialization failed (format={header['format']})", header["format"], header
        return fn, None, header["format"], header

    def _parse(self, raw: bytes, digest: str) -> Tuple[Optional[Dict], bytes, Optional[str]]:
        if not raw.startswith(_MAGIC):
            return None, b"", "bad magic (not an AOT artifact)"
        body = raw[len(_MAGIC):]
        if len(body) < _HEADER_LEN.size:
            return None, b"", "truncated header length"
        (hlen,) = _HEADER_LEN.unpack(body[: _HEADER_LEN.size])
        body = body[_HEADER_LEN.size:]
        if len(body) < hlen:
            return None, b"", "truncated header"
        try:
            header = json.loads(body[:hlen].decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError):
            return None, b"", "corrupt header json"
        payload = body[hlen:]
        if header.get("version") != _HEADER_VERSION:
            return None, b"", f"unsupported artifact version {header.get('version')}"
        if header.get("key_digest") != digest:
            return None, b"", "cache-key digest mismatch"
        if header.get("fingerprint") != _artifacts.backend_fingerprint():
            theirs, ours = header.get("fingerprint") or {}, _artifacts.backend_fingerprint()
            changed = sorted(k for k in set(theirs) | set(ours) if theirs.get(k) != ours.get(k))
            return None, b"", f"backend fingerprint mismatch ({', '.join(changed) or '?'})"
        if header.get("payload_sha256") != hashlib.sha256(payload).hexdigest():
            return None, b"", "payload checksum mismatch (corrupt artifact)"
        return header, payload, None

    # ------------------------------------------------------------------ store
    def store(
        self, kind: str, digest: str, fmt: str, payload: bytes, meta: Dict[str, Any]
    ) -> bool:
        """Atomically write one artifact; degrades (returns False) on IO errors."""
        header = {
            "version": _HEADER_VERSION,
            "format": fmt,
            "key_digest": digest,
            "payload_sha256": hashlib.sha256(payload).hexdigest(),
            "payload_bytes": len(payload),
            "fingerprint": _artifacts.backend_fingerprint(),
            "created": time.time(),
            **meta,
        }
        blob = json.dumps(header, sort_keys=True).encode("utf-8")
        final = self.artifact_path(kind, digest)
        tmp = final.with_name(final.name + f".tmp.{os.getpid()}")
        try:
            self.directory.mkdir(parents=True, exist_ok=True)
            with open(tmp, "wb") as fh:
                fh.write(_MAGIC + _HEADER_LEN.pack(len(blob)) + blob + payload)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, final)
            _fsync_dir(self.directory)
        except OSError as err:
            self._bump("write_errors")
            try:
                tmp.unlink(missing_ok=True)
            except OSError:
                pass
            _BUS.publish(
                "aot_cache_unwritable",
                "AotCache",
                f"artifact write failed: {type(err).__name__}: {err}",
                data={"kind": kind, "path": str(final)},
            )
            return False
        self._bump("writes")
        return True

    # ------------------------------------------------------------- inventory
    def entries(self) -> List[Dict[str, Any]]:
        """Header + integrity status of every artifact in the directory."""
        out: List[Dict[str, Any]] = []
        try:
            paths = sorted(self.directory.glob(f"*{_SUFFIX}"))
        except OSError:
            return out
        for path in paths:
            entry: Dict[str, Any] = {"path": str(path)}
            try:
                entry["file_bytes"] = path.stat().st_size
                raw = path.read_bytes()
            except OSError as err:
                # a concurrent evict can unlink between glob and stat/read:
                # report, don't crash the listing
                entry.setdefault("file_bytes", 0)
                entry["status"] = f"unreadable: {type(err).__name__}"
                out.append(entry)
                continue
            digest = path.name.rsplit(".", 2)[-2] if path.name.count(".") >= 2 else ""
            header, _payload, reason = self._parse_for_listing(raw)
            if header is None:
                entry["status"] = reason or "corrupt"
            else:
                entry.update(
                    {
                        "status": "ok",
                        "kind": header.get("kind", path.name.split(".", 1)[0]),
                        "owner": header.get("owner", "?"),
                        "format": header.get("format"),
                        "created": header.get("created"),
                        "fingerprint": header.get("fingerprint", {}),
                        "stale": header.get("fingerprint") != _artifacts.backend_fingerprint(),
                        "key_digest": header.get("key_digest", digest),
                    }
                )
            out.append(entry)
        return out

    def _parse_for_listing(self, raw: bytes) -> Tuple[Optional[Dict], bytes, Optional[str]]:
        """Like ``_parse`` but without a caller-supplied digest (CLI listing):
        verifies magic/header/checksum, flags (rather than fails) staleness."""
        if not raw.startswith(_MAGIC):
            return None, b"", "bad magic"
        body = raw[len(_MAGIC):]
        if len(body) < _HEADER_LEN.size:
            return None, b"", "truncated"
        (hlen,) = _HEADER_LEN.unpack(body[: _HEADER_LEN.size])
        body = body[_HEADER_LEN.size:]
        if len(body) < hlen:
            return None, b"", "truncated"
        try:
            header = json.loads(body[:hlen].decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError):
            return None, b"", "corrupt header"
        if header.get("version") != _HEADER_VERSION:
            # keep the listing's verdict aligned with the load path: an
            # artifact the runtime would refuse must not verify as "ok"
            return None, b"", f"unsupported artifact version {header.get('version')}"
        payload = body[hlen:]
        if header.get("payload_sha256") != hashlib.sha256(payload).hexdigest():
            return None, b"", "payload checksum mismatch"
        return header, payload, None

    def evict(
        self,
        *,
        stale_only: bool = False,
        kind: Optional[str] = None,
        entries: Optional[List[Dict[str, Any]]] = None,
    ) -> List[str]:
        """Delete artifacts (all, one kind, or only fingerprint-stale/corrupt).

        ``entries`` lets a caller that already listed the store (the CLI's
        confirmation pass) skip a second full read+checksum sweep.
        """
        removed: List[str] = []
        for entry in entries if entries is not None else self.entries():
            if kind is not None and entry.get("kind") != kind:
                continue
            if stale_only and entry.get("status") == "ok" and not entry.get("stale"):
                continue
            try:
                os.unlink(entry["path"])
                removed.append(entry["path"])
            except OSError:
                continue
        return removed


# one AotCache per directory, so re-pointing the cache mid-process works and
# every dispatcher created while a directory was active keeps using it
_CACHES: Dict[str, AotCache] = {}
_CACHES_LOCK = _san_lock("aot._CACHES_LOCK")


def get_cache(directory: Optional[str] = None) -> Optional[AotCache]:
    path = directory if directory is not None else AOT.cache_dir
    if not path:
        return None
    with _CACHES_LOCK:
        cache = _CACHES.get(path)
        if cache is None:
            cache = _CACHES[path] = AotCache(path)
        return cache


def aot_stats() -> Dict[str, int]:
    """Process-wide AOT counters summed over every active cache directory."""
    with _CACHES_LOCK:
        caches = list(_CACHES.values())
    totals: Dict[str, int] = {}
    for cache in caches:
        for key, val in cache.stats().items():
            totals[key] = totals.get(key, 0) + val
    return totals


def reset_aot_stats() -> None:
    with _CACHES_LOCK:
        caches = list(_CACHES.values())
    for cache in caches:
        with cache._lock:
            for key in cache._stats:
                cache._stats[key] = 0


# ALL dispatchers serialize cold resolution through this one lock: resolving
# traces the owner's update body, and tracing mutates instance-bound caches
# (traced closures, lazily-shaped states) that two concurrent lowerings —
# even of DIFFERENT signatures — would corrupt into wrong-arity executables.
# Steady-state dispatch (the `_resolved` probe) never touches it, and the
# disk reads/writes it covers are one-time per (process, signature).
_RESOLVE_LOCK = _san_lock("aot._RESOLVE_LOCK")


class _AotDispatch:
    """Per-executable dispatcher: concrete call signature -> ready executable.

    Wraps ONE freshly-jitted callable (one structural cache-key slot in the
    owner's compile cache). Per distinct aval signature it resolves exactly
    once — disk hit, or lower+compile+persist — then steady-state calls pay
    a tree-flatten + dict probe before invoking the executable directly.
    Every AOT-machinery failure permanently falls back to the plain jitted
    callable for that signature: results are never wrong, only cold.

    Thread-safety: cold resolution is serialized process-wide under
    ``_RESOLVE_LOCK`` (see above) with a double-probe of ``_resolved`` so
    the losing thread adopts the winner's executable; steady-state reads are
    GIL-atomic dict probes. The disk layer beneath is lock-guarded only
    around its stats.
    """

    __slots__ = (
        "_jit_fn", "_owner", "_kind", "_key_repr", "_telem_obj", "_use_disk",
        "_resolved", "_fast", "_cost_claim",
    )

    def __init__(
        self,
        jit_fn: Callable,
        owner: str,
        kind: str,
        key_repr: str,
        telem_obj: Any = None,
        use_disk: bool = True,
        cost_claim: Optional[Callable[[tuple], Any]] = None,
    ) -> None:
        self._jit_fn = jit_fn
        self._owner = owner
        self._kind = kind
        self._key_repr = key_repr
        self._telem_obj = telem_obj
        self._use_disk = use_disk
        # closed-form ExecutableCost claim computed from the concrete call
        # args — authoritative for executables XLA cannot price (Pallas ops
        # report zero flops to cost_analysis(), which would zero the MFU
        # gauges); persisted in the artifact header like an extracted cost
        self._cost_claim = cost_claim
        self._resolved: Dict[Any, Callable] = {}
        # steady-state fast slot: every seam's structural cache key already
        # pins arg structure + shapes + dtypes, so a dispatcher normally sees
        # exactly ONE aval signature — once it resolves, repeat calls skip
        # the per-call tree-flatten + abstractify probe (~2us, ~4% of a
        # compiled default update) and invoke the executable directly. The
        # executable validates input avals itself: genuine drift raises
        # TypeError BEFORE executing, landing in the keyed path below.
        self._fast: Optional[Callable] = None

    def __call__(self, *args: Any) -> Any:
        fast = self._fast
        if fast is not None:
            try:
                return fast(*args)
            except (TypeError, ValueError):
                # aval drift: re-dispatch through the keyed path. Both types
                # matter: xla_exec executables reject a mismatched call with
                # TypeError, stablehlo-loaded ones with ValueError — and
                # both reject BEFORE executing, so no buffer is consumed.
                pass
        sig = _aval_signature(args)
        fn = self._resolved.get(sig)
        if fn is None:
            fn = self._resolve(sig, args)
        try:
            return fn(*args)
        except (TypeError, ValueError):
            if fn is self._jit_fn:
                raise
            # a loaded executable REJECTING the call convention (aval drift
            # the signature missed) must not poison the stream: re-route
            # through the ordinary jitted path and pin it for this signature.
            # Only call-convention rejections re-route — a runtime fault
            # (collective failure, injected fault) must propagate untouched
            # so the engine/pool degradation handlers see the real error,
            # not a replay against possibly-donated buffers.
            self._note_fallback("loaded executable rejected the call")
            self._resolved[sig] = self._jit_fn
            if self._fast is fn:
                self._fast = None
            return self._jit_fn(*args)

    def warm(self, *args: Any) -> str:
        """Resolve (load or compile+persist) WITHOUT executing.

        Returns ``"hit"`` (loaded from disk), ``"compiled"`` (traced and, with
        a cache directory set, persisted), or ``"fallback"`` (AOT machinery
        unavailable; the plain jitted callable will serve the signature).
        """
        sig = _aval_signature(args)
        fn = self._resolved.get(sig)
        if fn is not None:
            return "hit" if fn is not self._jit_fn else "fallback"
        return self._resolve(sig, args, outcome=True)

    # ------------------------------------------------------------- resolution
    def _resolve(self, sig: Any, args: tuple, outcome: bool = False) -> Any:
        with _RESOLVE_LOCK:
            fn = self._resolved.get(sig)
            if fn is not None:
                # another thread resolved this signature while we waited for
                # the lock: adopt its executable — reported as a hit (it is
                # warm) unless it pinned the plain jitted fallback
                result = "hit" if fn is not self._jit_fn else "fallback"
                return result if outcome else fn
            return self._resolve_traced(sig, args, outcome)

    def _resolve_traced(self, sig: Any, args: tuple, outcome: bool) -> Any:
        _sp = None
        if _OBS.tracing:
            _sp = _obs_trace.begin_span("aot.load", self._owner, kind=self._kind)
        try:
            result, fn = self._resolve_inner(sig, args)
        except BaseException as err:  # pragma: no cover - defensive
            if _sp is not None:
                _obs_trace.end_span(_sp, err)
            raise
        if _sp is not None:
            _sp.attrs["outcome"] = result
            _obs_trace.end_span(_sp)
        return result if outcome else fn

    def _resolve_inner(self, sig: Any, args: tuple) -> Tuple[str, Callable]:
        cache = get_cache() if self._use_disk else None
        digest = None
        avoid_fmt = None
        if cache is not None:
            try:
                digest = _digest(self._owner, self._kind, self._key_repr, sig)
                fn, reason, stored_fmt, header = cache.load(self._kind, digest)
            except Exception as err:  # noqa: BLE001 - cache failure never breaks the stream
                fn, reason, stored_fmt, header = (
                    None, f"cache probe failed: {type(err).__name__}: {err}", None, None,
                )
            if fn is not None:
                cache._bump("hits", self._telem_obj, "hit")
                self._resolved[sig] = fn
                self._fast = fn if len(self._resolved) == 1 else None
                if _OBS.profiling:
                    # a disk hit skips lower+compile, so cost_analysis() is
                    # unreachable — the artifact header carried it forward
                    self._note_cost(_cost_from_header(header), digest, 0.0, "aot_hit")
                return "hit", fn
            if reason is not None:
                self._note_fallback(reason, cache)
                if reason.startswith("deserialization failed"):
                    # self-heal: the payload only fails to deserialize in a
                    # fresh process (process-local JIT symbols) — re-storing
                    # the same format would wedge every future replica, so
                    # rebuild with the next format down the ladder
                    avoid_fmt = stored_fmt
            else:
                cache._bump("misses", self._telem_obj, "miss")
        t_compile = time.perf_counter()
        compiled, fmt, payload = _artifacts.build_artifact(
            self._jit_fn, args, avoid_format=avoid_fmt, want_payload=cache is not None
        )
        compile_seconds = time.perf_counter() - t_compile
        if compiled is None:
            # lowering failed (e.g. non-jittable leftovers): the plain jitted
            # call will surface the real error to the caller's own handler
            self._resolved[sig] = self._jit_fn
            self._fast = None
            if cache is not None:
                self._note_fallback("lowering failed", cache)
            return "fallback", self._jit_fn
        self._resolved[sig] = compiled
        self._fast = compiled if len(self._resolved) == 1 else None
        cost = _costs.extract_cost(compiled) if (cache is not None or _OBS.profiling) else None
        claim = self._claimed_cost(args) if (cache is not None or _OBS.profiling) else None
        if claim is not None:
            cost = claim
        if _OBS.profiling:
            if digest is None:
                digest = _digest(self._owner, self._kind, self._key_repr, sig)
            self._note_cost(cost, digest, compile_seconds, "compiled")
        if cache is not None and digest is not None and fmt is not None:
            meta: Dict[str, Any] = {
                "owner": self._owner,
                "kind": self._kind,
                "key": self._key_repr,
                "compile_seconds": compile_seconds,
            }
            if cost is not None:
                meta["cost_flops"] = cost.flops
                meta["cost_bytes_accessed"] = cost.bytes_accessed
            cache.store(self._kind, digest, fmt, payload, meta)
        elif cache is not None:
            self._note_fallback("no serialization format available", cache)
        return "compiled", compiled

    def _claimed_cost(self, args: tuple) -> Optional[Any]:
        """Evaluate the closed-form cost claim; claim failures never break dispatch."""
        if self._cost_claim is None:
            return None
        try:
            return self._cost_claim(args)
        except Exception:  # noqa: BLE001 - pricing is best-effort
            return None

    def _note_cost(
        self, cost: Optional[Any], digest: Optional[str], compile_seconds: float, source: str
    ) -> None:
        """Report one resolved executable to the profiling cost ledger."""
        from torchmetrics_tpu._observability.profiling import LEDGER

        LEDGER.note_executable(
            owner=self._owner,
            kind=self._kind,
            digest=digest or "",
            cost=cost,
            compile_seconds=compile_seconds,
            source=source,
        )

    def _note_fallback(self, reason: str, cache: Optional[AotCache] = None) -> None:
        cache = cache if cache is not None else get_cache() if self._use_disk else None
        if cache is not None:
            cache._bump("fallbacks", self._telem_obj, "fallback")
        elif self._telem_obj is not None and _OBS.enabled:
            from torchmetrics_tpu._observability.telemetry import telemetry_for

            telemetry_for(self._telem_obj).inc("aot_cache|result=fallback")
        _BUS.publish(
            "aot_fallback",
            self._owner,
            f"{self._kind}: {reason}",
            data={"kind": self._kind, "reason": reason},
        )


def wrap_executable(
    jit_fn: Callable,
    *,
    owner: str,
    kind: str,
    key_repr: str,
    telem_obj: Any = None,
    use_disk: Optional[bool] = None,
    cost_claim: Optional[Callable[[tuple], Any]] = None,
) -> _AotDispatch:
    """Wrap a fresh jitted callable in the AOT dispatcher.

    ``use_disk=None`` follows the process switch at call time (the usual
    seam integration); ``False`` builds a memory-only dispatcher — used by
    ``warm_start()`` so explicit pre-compilation works even without a cache
    directory. ``cost_claim`` (concrete call args -> ``ExecutableCost``)
    prices executables XLA's cost analysis cannot see into — the Pallas
    kernels pass their closed-form flop/byte claims here.
    """
    return _AotDispatch(
        jit_fn,
        owner=owner,
        kind=kind,
        key_repr=key_repr,
        telem_obj=telem_obj,
        use_disk=AOT.active if use_disk is None else use_disk,
        cost_claim=cost_claim,
    )
