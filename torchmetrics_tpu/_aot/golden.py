"""Golden compile-count manifest for the certified default path.

The recompile-churn detector (PR 10) made recompiles *detectable*; this
module makes them *preventable*: ``_analysis/compile_golden.json`` pins the
exact set of compiled-executable cache keys the certified default-path sweep
(``default_path.py``) is allowed to produce, and the tier-1 gate fails any
PR whose sweep builds a key beyond the manifest — with the differing
component(s) named by the same diff the churn warning uses at runtime
(:func:`~torchmetrics_tpu._observability.telemetry.diff_components`).

Regenerate after an intentional compile-surface change with::

    python tools/compile_golden.py --write
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["GOLDEN_PATH", "load_golden", "observed_to_json", "write_golden", "check_observed"]

GOLDEN_PATH = Path(__file__).resolve().parents[1] / "_analysis" / "compile_golden.json"
_VERSION = 1

_Key = Tuple[str, Tuple[Tuple[str, str], ...]]


def _keyset(entries: List[Dict[str, Any]]) -> Dict[_Key, Dict[str, str]]:
    out: Dict[_Key, Dict[str, str]] = {}
    for entry in entries:
        components = {str(k): str(v) for k, v in entry["components"].items()}
        out[(entry["kind"], tuple(sorted(components.items())))] = components
    return out


def load_golden(path: Optional[Path] = None) -> Dict[str, List[Dict[str, Any]]]:
    blob = json.loads((path or GOLDEN_PATH).read_text(encoding="utf-8"))
    if blob.get("version") != _VERSION:
        raise ValueError(f"unsupported compile_golden.json version {blob.get('version')}")
    return blob["classes"]


def observed_to_json(observed: Dict[str, List[Dict[str, Any]]]) -> Dict[str, Any]:
    return {
        "version": _VERSION,
        "classes": {
            name: sorted(entries, key=lambda e: (e["kind"], sorted(e["components"].items())))
            for name, entries in sorted(observed.items())
        },
    }


def write_golden(path: Optional[Path] = None) -> Dict[str, Any]:
    from torchmetrics_tpu._aot.default_path import drive_default_path

    blob = observed_to_json(drive_default_path())
    target = path or GOLDEN_PATH
    target.write_text(json.dumps(blob, indent=1, sort_keys=True) + "\n", encoding="utf-8")
    return blob


def check_observed(
    observed: Dict[str, List[Dict[str, Any]]],
    golden: Dict[str, List[Dict[str, Any]]],
) -> List[str]:
    """Compare a sweep against the golden manifest; return gate failures.

    A compile key beyond the manifest is a *recompile regression* — reported
    with the churn detector naming which cache-key component(s) moved
    relative to the nearest same-kind golden key. A golden key the sweep no
    longer produces (or a class disappearing) means the manifest is *stale*
    and must be regenerated.
    """
    from torchmetrics_tpu._observability.telemetry import diff_components

    problems: List[str] = []
    for name in sorted(set(observed) | set(golden)):
        if name not in golden:
            problems.append(
                f"{name}: not in the golden manifest — new certified default-path class;"
                " regenerate with `python tools/compile_golden.py --write`"
            )
            continue
        if name not in observed:
            problems.append(
                f"{name}: golden manifest lists it but the sweep no longer drives it —"
                " stale manifest; regenerate with `python tools/compile_golden.py --write`"
            )
            continue
        got = _keyset(observed[name])
        want = _keyset(golden[name])
        for key, components in got.items():
            if key in want:
                continue
            kind = key[0]
            same_kind = [c for (k, _), c in want.items() if k == kind]
            if same_kind:
                changed, diff = diff_components(same_kind[0], components)
                problems.append(
                    f"{name}: NEW `{kind}` compile beyond the golden manifest — changed"
                    f" cache-key component(s): {', '.join(changed) or '?'} ({diff})"
                )
            else:
                problems.append(
                    f"{name}: NEW executable kind `{kind}` on the certified default path"
                    f" (components: {components})"
                )
        for key in want:
            if key not in got:
                problems.append(
                    f"{name}: golden `{key[0]}` key no longer produced by the sweep —"
                    " stale manifest; regenerate with `python tools/compile_golden.py --write`"
                )
    return problems
