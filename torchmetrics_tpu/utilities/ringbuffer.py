"""Fixed-capacity device ring buffers for append-mode ("cat") metric states.

The reference's list states grow without bound (``metric.py:195-272`` registers
plain python lists; ``metric.py:483-488`` relieves memory only by moving them to
CPU). On TPU the idiomatic design (SURVEY §5/§7) is a *fixed-capacity* ring
buffer: one preallocated ``(capacity, *item_shape)`` device array plus a
validity mask, updated with XLA scatter — static shapes, jit-compatible,
bounded HBM, and shardable/gatherable like any other array state.

Two layers:

- :class:`RingBuffer` — a mutable host-side container that quacks like the
  list states metrics already use (``.append``, iteration via ``values()``),
  registered as a pytree so it can also flow through ``jit``/``shard_map``.
- Pure kernels (:func:`ring_push`) for fully functional in-jit use.

Metrics opt in per-instance with the ``cat_state_capacity`` constructor kwarg
(consumed by the ``Metric`` base class): every list state declared with
``dist_reduce_fx="cat"`` is transparently replaced by a ring buffer of that
capacity. Once more rows than ``capacity`` have been appended, the oldest rows
are overwritten (a one-time warning is emitted) — the deliberate bounded-memory
trade-off for streaming quantile/curve/retrieval states at scale.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


def ring_push(data: Array, valid: Array, count: Array, batch: Array) -> Tuple[Array, Array, Array]:
    """Pure ring-buffer push: scatter ``batch`` rows in at the write cursor.

    All shapes are static (``batch``'s leading dim is a trace-time constant), so
    this compiles to a single XLA scatter — usable inside ``jit``/``scan``.

    Args:
        data: ``(capacity, *item_shape)`` storage.
        valid: ``(capacity,)`` bool validity mask.
        count: scalar int32, total rows ever pushed (the write cursor is
            ``count % capacity``).
        batch: ``(n, *item_shape)`` rows to insert. If ``n > capacity`` only
            the last ``capacity`` rows survive.

    Returns:
        Updated ``(data, valid, count)``; ``count`` grows by the full ``n``.
    """
    capacity = data.shape[0]
    n = batch.shape[0]
    if n > capacity:
        batch = batch[-capacity:]
        offset = n - capacity
    else:
        offset = 0
    idx = (count + offset + jnp.arange(batch.shape[0], dtype=jnp.int32)) % capacity
    data = data.at[idx].set(batch.astype(data.dtype))
    valid = valid.at[idx].set(True)
    return data, valid, count + jnp.int32(n)


class RingBuffer:
    """Fixed-capacity device buffer standing in for an append-mode list state.

    Storage is allocated lazily on the first :meth:`append` (item shape and
    dtype are taken from the first batch), so it can be declared before the
    metric has seen data — exactly like an empty list state.
    """

    def __init__(
        self,
        capacity: int,
        item_shape: Optional[Sequence[int]] = None,
        dtype: Any = None,
        _data: Optional[Array] = None,
        _valid: Optional[Array] = None,
        _count: Optional[Array] = None,
    ) -> None:
        if not (isinstance(capacity, int) and capacity > 0):
            raise ValueError(f"Argument `capacity` must be a positive integer, but got {capacity}")
        self.capacity = capacity
        if _data is not None:
            self.data = _data
            self.valid = _valid
            self.count = _count
        elif item_shape is not None and dtype is not None:
            self.data = jnp.zeros((capacity, *item_shape), dtype)
            self.valid = jnp.zeros((capacity,), bool)
            self.count = jnp.zeros((), jnp.int32)
        else:
            self.data = None
            self.valid = None
            self.count = jnp.zeros((), jnp.int32)
        # host-side mirror of `count` so the overflow check never forces a
        # device sync; None when unknown (buffer built from device arrays)
        self._host_count: Optional[int] = 0 if _count is None else None
        self._warned_overflow = False

    # ------------------------------------------------------------- properties
    @property
    def initialized(self) -> bool:
        return self.data is not None

    @property
    def item_shape(self) -> Optional[Tuple[int, ...]]:
        return None if self.data is None else self.data.shape[1:]

    @property
    def num_valid(self) -> int:
        """Number of live rows (concrete; host-side)."""
        return 0 if self.valid is None else int(jnp.sum(self.valid))

    @property
    def num_dropped(self) -> int:
        """Rows overwritten because more than ``capacity`` were appended."""
        total = self._host_count if self._host_count is not None else int(self.count)
        return max(0, total - self.capacity)

    def __len__(self) -> int:
        return self.num_valid

    def __repr__(self) -> str:
        shape = None if self.data is None else tuple(self.data.shape)
        return f"RingBuffer(capacity={self.capacity}, shape={shape}, valid={self.num_valid})"

    # ----------------------------------------------------------------- update
    def append(self, x: Any) -> "RingBuffer":
        """Insert the rows of ``x`` (its leading axis; scalars become one row)."""
        batch = jnp.atleast_1d(jnp.asarray(x))
        if self.data is None:
            self.data = jnp.zeros((self.capacity, *batch.shape[1:]), batch.dtype)
            self.valid = jnp.zeros((self.capacity,), bool)
        if batch.shape[1:] != self.data.shape[1:]:
            raise ValueError(
                f"RingBuffer expects rows of shape {self.data.shape[1:]}, but got a batch of shape {batch.shape}"
            )
        from torchmetrics_tpu.utilities.checks import _is_concrete

        if not _is_concrete(self.count):
            # inside jit the occupancy is unknown at trace time; overflow
            # bookkeeping resumes on the next eager append
            self._host_count = None
        else:
            if self._host_count is None:  # one-time readback for device-built buffers
                self._host_count = int(self.count)
            will_drop = self._host_count + batch.shape[0] > self.capacity
            self._host_count += batch.shape[0]
            if will_drop and not self._warned_overflow:
                rank_zero_warn(
                    f"RingBuffer capacity ({self.capacity}) exceeded; oldest rows are being overwritten."
                    " Increase `cat_state_capacity` if the metric should see every sample.",
                    UserWarning,
                )
                self._warned_overflow = True
        self.data, self.valid, self.count = ring_push(self.data, self.valid, self.count, batch)
        return self

    def _sync_host_count(self, host_count: Optional[int]) -> None:
        """Restore host-side overflow bookkeeping after a traced push.

        Compiled updates (``jit_update``/``scan_update``/auto-compiled
        ``update``) push rows under trace, where the occupancy check cannot
        run; the metric runtime re-derives the host count afterwards (one
        readback per argument signature) and hands it back here so the
        capacity-overflow warning still fires.
        """
        self._host_count = host_count
        if host_count is not None and host_count > self.capacity and not self._warned_overflow:
            rank_zero_warn(
                f"RingBuffer capacity ({self.capacity}) exceeded; oldest rows are being overwritten."
                " Increase `cat_state_capacity` if the metric should see every sample.",
                UserWarning,
            )
            self._warned_overflow = True

    def extend(self, values: Any) -> "RingBuffer":
        """Append an iterable of batches, another :class:`RingBuffer`, or one array."""
        if isinstance(values, RingBuffer):
            if values.num_valid:
                self.append(values.values())
        elif isinstance(values, (list, tuple)):
            for v in values:
                self.append(v)
        else:
            self.append(values)
        return self

    # ------------------------------------------------------------------ reads
    def values(self) -> Array:
        """The live rows as one ``(num_valid, *item_shape)`` array (host path).

        Row order follows storage order, not insertion order, once the buffer
        has wrapped or been merged — cat states are order-agnostic reductions.
        """
        if self.data is None:
            return jnp.zeros((0,), jnp.float32)
        mask = np.asarray(self.valid)
        return self.data[jnp.asarray(np.nonzero(mask)[0])]

    def masked(self) -> Tuple[Array, Array]:
        """``(data, valid)`` with static shapes — the jit-safe accessor."""
        if self.data is None:
            raise ValueError("RingBuffer has no storage yet (nothing appended)")
        return self.data, self.valid

    # ------------------------------------------------------------ lifecycle
    def copy(self) -> "RingBuffer":
        out = RingBuffer(self.capacity, _data=self.data, _valid=self.valid, _count=self.count)
        out._host_count = self._host_count
        out._warned_overflow = self._warned_overflow
        return out

    def copy_empty(self) -> "RingBuffer":
        """A fresh buffer with the same capacity (storage re-lazied)."""
        return RingBuffer(self.capacity)

    def to_device(self, device: Any) -> "RingBuffer":
        if self.data is not None:
            self.data = jax.device_put(self.data, device)
            self.valid = jax.device_put(self.valid, device)
        self.count = jax.device_put(self.count, device)
        return self

    # -------------------------------------------------------------- pickling
    def __getstate__(self) -> dict:
        state = dict(self.__dict__)
        for key in ("data", "valid", "count"):
            if state[key] is not None:
                state[key] = np.asarray(state[key])
        return state

    def __setstate__(self, state: dict) -> None:
        self.__dict__.update(state)
        for key in ("data", "valid", "count"):
            if getattr(self, key) is not None:
                setattr(self, key, jnp.asarray(getattr(self, key)))


def _ringbuffer_flatten(rb: RingBuffer):
    if rb.data is None:
        raise ValueError("Cannot trace an uninitialized RingBuffer (append at least one batch first)")
    return (rb.data, rb.valid, rb.count), rb.capacity


def _ringbuffer_unflatten(capacity, leaves):
    data, valid, count = leaves
    # leaf shapes may legitimately differ from `capacity` after an in-jit
    # all_gather (world concat); trust the leaves
    cap = int(data.shape[0]) if hasattr(data, "shape") and data.shape else capacity
    return RingBuffer(cap, _data=data, _valid=valid, _count=count)


jax.tree_util.register_pytree_node(RingBuffer, _ringbuffer_flatten, _ringbuffer_unflatten)
