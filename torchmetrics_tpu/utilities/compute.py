"""Safe math primitives shared by all metric kernels.

Parity target: reference ``torchmetrics/utilities/compute.py:20-157``. All
functions are pure ``jax.numpy`` and jit-safe (static shapes in, static shapes
out); division-by-zero is handled with ``jnp.where`` instead of host branching
so the MXU pipeline is never broken by data-dependent control flow.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

Array = jax.Array


def _safe_matmul(x: Array, y: Array) -> Array:
    """Matmul that promotes half-precision inputs to float32 for MXU accumulation.

    ``precision="highest"`` keeps f32 operands at full precision on the TPU
    MXU (the default silently rounds them to bf16, shifting pairwise
    similarity values off the reference).
    """
    if x.dtype in (jnp.float16, jnp.bfloat16) or y.dtype in (jnp.float16, jnp.bfloat16):
        return jnp.matmul(
            x.astype(jnp.float32), y.astype(jnp.float32).T, precision="highest"
        ).astype(x.dtype)
    return jnp.matmul(x, y.T, precision="highest")


def _mxu_precision(dtype):
    """f32 weights on the TPU MXU silently drop to bf16 passes; request full
    precision unless the caller explicitly chose a half compute dtype."""
    return "highest" if dtype in (None, jnp.float32) else None


def _safe_sqrt(x: Array) -> Array:
    """``sqrt`` with a finite (zero) gradient at 0.

    Plain ``sqrt`` has an infinite derivative at 0, which turns masked-out
    zero distances (diagonals, own-centroid terms) into NaN gradients — the
    classic where-after-sqrt trap.  Negative inputs map to 0 (callers pass
    sums of squares).
    """
    positive = x > 0
    return jnp.where(positive, jnp.sqrt(jnp.where(positive, x, 1.0)), 0.0)


def _safe_pow(base: Array, exp: Array) -> Array:
    """``base ** exp`` with finite gradients where the true derivative diverges.

    Forward semantics are unchanged — including ``0 ** 0 == 1`` and NaN for
    negative bases with fractional exponents — but the non-positive branch is
    computed on a stopped-gradient base, so autodiff at ``base == 0`` with
    ``exp < 1`` yields 0 instead of inf.
    """
    positive = base > 0
    safe = jnp.where(positive, base, 1.0) ** exp
    return jnp.where(positive, safe, jax.lax.stop_gradient(base) ** exp)


def _safe_xlogy(x: Array, y: Array) -> Array:
    """``x * log(y)`` that is 0 whenever ``x == 0`` (even when ``y == 0``)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    res = x * jnp.log(jnp.where(x == 0, jnp.ones_like(y), y))
    return jnp.where(x == 0.0, jnp.zeros_like(res), res)


def _safe_divide(num: Array, denom: Array, zero_division: float = 0.0) -> Array:
    """Elementwise division returning ``zero_division`` where ``denom == 0``."""
    num = jnp.asarray(num)
    denom = jnp.asarray(denom)
    if not jnp.issubdtype(num.dtype, jnp.floating):
        num = num.astype(jnp.float32)
    if not jnp.issubdtype(denom.dtype, jnp.floating):
        denom = denom.astype(jnp.float32)
    ones = jnp.ones_like(denom)
    res = num / jnp.where(denom == 0, ones, denom)
    return jnp.where(denom == 0, jnp.full_like(res, zero_division), res)


def _adjust_weights_safe_divide(
    score: Array,
    average: Optional[str],
    multilabel: bool,
    tp: Array,
    fp: Array,
    fn: Array,
    zero_division: float = 0.0,
) -> Array:
    """Apply macro/weighted averaging over per-class scores.

    Parity: reference ``torchmetrics/utilities/compute.py:57-68``. Classes that
    never appear (``tp+fp+fn == 0``) are dropped from the macro average unless
    running multilabel.
    """
    if average is None or average == "none":
        return score
    if average == "weighted":
        weights = (tp + fn).astype(jnp.float32)
    else:
        weights = jnp.ones_like(score)
        if not multilabel:
            weights = jnp.where(tp + fp + fn == 0, 0.0, weights)
    return _safe_divide(
        jnp.sum(weights * score, axis=-1),
        jnp.sum(weights, axis=-1),
        zero_division,
    )


def _auc_compute_without_check(x: Array, y: Array, direction: float, axis: int = -1) -> Array:
    """Trapezoidal area under (x, y) assuming x already sorted in ``direction``."""
    dx = jnp.diff(x, axis=axis)
    if axis == -1 or axis == x.ndim - 1:
        y_avg = (y[..., :-1] + y[..., 1:]) / 2.0
    else:
        y0 = jax.lax.slice_in_dim(y, 0, y.shape[axis] - 1, axis=axis)
        y1 = jax.lax.slice_in_dim(y, 1, y.shape[axis], axis=axis)
        y_avg = (y0 + y1) / 2.0
    return jnp.sum(y_avg * dx, axis=axis) * direction


def _auc_compute(x: Array, y: Array, reorder: bool = False) -> Array:
    """Area under curve. With ``reorder`` the points are sorted by x first.

    Unlike the reference (``utilities/compute.py:95-130``) we do not
    data-dependently branch on monotonicity (not jit-compatible); the sign of the
    mean step determines direction.
    """
    if reorder:
        order = jnp.argsort(x, stable=True)
        x = x[order]
        y = y[order]
    dx = jnp.diff(x)
    direction = jnp.where(jnp.sum(dx) >= 0, 1.0, -1.0)
    return _auc_compute_without_check(x, y, 1.0) * direction


def auc(x: Array, y: Array, reorder: bool = False) -> Array:
    """Public AUC entry point (functional parity with reference ``functional.auc``)."""
    return _auc_compute(x, y, reorder=reorder)


def interp(x: Array, xp: Array, fp: Array) -> Array:
    """1-D linear interpolation with the reference's exact semantics.

    Reference ``utilities/compute.py:134-157``: segment index = count of
    ``xp`` values ≤ x (clamped), slopes taken in ``xp``'s original order,
    linear extrapolation past the ends. This differs from ``jnp.interp``
    (which clamps at the boundary and assumes sorted ``xp``) — the macro
    curve merges call it on non-monotonic ``xp``, where the count-based
    segment pick is part of the observable behavior.
    """
    x, xp, fp = jnp.asarray(x), jnp.asarray(xp), jnp.asarray(fp)
    scalar_x = x.ndim == 0
    x1 = jnp.atleast_1d(x)
    if xp.shape[0] < 2:  # degenerate: no segments to interpolate over
        out = jnp.broadcast_to(fp[0] if fp.size else jnp.nan, x1.shape)
        return out[0] if scalar_x else out
    m = _safe_divide(fp[1:] - fp[:-1], xp[1:] - xp[:-1])
    b = fp[:-1] - m * xp[:-1]
    indices = jnp.sum(x1[:, None] >= xp[None, :], axis=1) - 1
    indices = jnp.clip(indices, 0, m.shape[0] - 1)
    out = m[indices] * x1 + b[indices]
    return out[0] if scalar_x else out


def normalize_logits_if_needed(tensor: Array, normalization: Optional[str]) -> Array:
    """Apply sigmoid/softmax iff values fall outside [0, 1].

    The reference checks ``tensor.min() < 0 or tensor.max() > 1`` eagerly
    (``functional/classification/*_format``); under jit that is a traced bool, so
    we compute it as a lax.cond-free ``jnp.where`` over the whole array.
    """
    if normalization is None or tensor.size == 0:
        # size-0: reference's torch.all on empty is True -> no normalization
        return tensor
    outside = (jnp.min(tensor) < 0) | (jnp.max(tensor) > 1)
    if normalization == "sigmoid":
        return jnp.where(outside, jax.nn.sigmoid(tensor), tensor)
    if normalization == "softmax":
        return jnp.where(outside, jax.nn.softmax(tensor, axis=1), tensor)
    raise ValueError(f"Unknown normalization: {normalization}")
