"""Lazy optional-dependency registry.

Parity target: reference ``torchmetrics/utilities/imports.py:24-68`` (~35
``RequirementCache`` flags). We keep the same lattice idea with a lightweight
probe that never imports at module load.
"""

from __future__ import annotations

import importlib.util
from functools import lru_cache


@lru_cache(maxsize=None)
def package_available(name: str) -> bool:
    """True iff ``name`` is importable (spec probe only, no import side effects)."""
    try:
        return importlib.util.find_spec(name) is not None
    except (ModuleNotFoundError, ValueError):
        return False


class RequirementCache:
    """Boolean-ish lazy probe for an optional dependency."""

    def __init__(self, module: str) -> None:
        self.module = module

    def __bool__(self) -> bool:
        return package_available(self.module)

    def __repr__(self) -> str:
        return f"RequirementCache({self.module}={bool(self)})"


_MATPLOTLIB_AVAILABLE = RequirementCache("matplotlib")
_SCIPY_AVAILABLE = RequirementCache("scipy")
_SKLEARN_AVAILABLE = RequirementCache("sklearn")
_TRANSFORMERS_AVAILABLE = RequirementCache("transformers")
_NLTK_AVAILABLE = RequirementCache("nltk")
_TORCH_AVAILABLE = RequirementCache("torch")
_FLAX_AVAILABLE = RequirementCache("flax")
_PANDAS_AVAILABLE = RequirementCache("pandas")
_REGEX_AVAILABLE = RequirementCache("regex")
_PESQ_AVAILABLE = RequirementCache("pesq")
_PYSTOI_AVAILABLE = RequirementCache("pystoi")
# kept for reference imports-registry parity; SRMR itself is self-contained
_GAMMATONE_AVAILABLE = RequirementCache("gammatone")
_LIBROSA_AVAILABLE = RequirementCache("librosa")
_PYCOCOTOOLS_AVAILABLE = RequirementCache("pycocotools")
_MECAB_AVAILABLE = RequirementCache("MeCab")
_SENTENCEPIECE_AVAILABLE = RequirementCache("sentencepiece")
