"""Input validation helpers (host-side, run outside jit).

Parity target: reference ``torchmetrics/utilities/checks.py:33-296``. Validation
inspects *static* properties (shape, dtype, rank) wherever possible so it can
run on traced values; value-dependent checks (label ranges, prob bounds) pull to
host and are therefore only executed on concrete arrays — they are skipped
automatically under jit, matching the ``validate_args=False`` fast path of the
reference.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.utilities.prints import rank_zero_only


@rank_zero_only
def rank_zero_print(*args, **kwargs) -> None:
    print(*args, **kwargs)

Array = jax.Array


def _is_concrete(x) -> bool:
    """True when ``x`` holds real values (not a tracer) so host checks can run."""
    return not isinstance(x, jax.core.Tracer)


def _check_same_shape(preds: Array, target: Array) -> None:
    """Raise if shapes differ (reference ``checks.py:33-39``)."""
    if preds.shape != target.shape:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, but got {preds.shape} and {target.shape}."
        )


def _is_floating(x: Array) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def _check_valid_prob_values(x: Array, name: str = "preds") -> None:
    if _is_concrete(x) and ((np.asarray(x) < 0).any() or (np.asarray(x) > 1).any()):
        raise ValueError(f"Expected {name} to be probabilities in [0,1], but values outside the range were found.")


def _check_label_range(x: Array, num_classes: int, name: str = "target", allow_ignore: Optional[int] = None) -> None:
    if not _is_concrete(x):
        return
    arr = np.asarray(x)
    if allow_ignore is not None:
        arr = arr[arr != allow_ignore]
    if arr.size and (arr.min() < 0 or arr.max() >= num_classes):
        raise RuntimeError(
            f"Detected more unique values in `{name}` than expected. Expected only {num_classes} but found "
            f"values in range [{arr.min()}, {arr.max()}]."
        )


def _num_samples_check(preds: Array, target: Array) -> None:
    if preds.shape[0] != target.shape[0]:
        raise RuntimeError("Predictions and targets must have the same number of samples.")


# ------------------------------------------------------------------ traced
# Building blocks for ``Metric._traced_value_flags`` (the fused-validation
# contract of the compiled ``validate_args=True`` path): each returns a
# static message tuple plus a same-length boolean violation vector computed
# with jnp ops only. The message tuple — and therefore the flag length —
# must be identical across every argument signature of a metric instance
# (dtype-inapplicable checks contribute a constant-False flag, never a
# missing entry), so the device-side OR accumulator stays aligned.


def _target_set_value_flags(target: Array, ignore_index: Optional[int] = None):
    """Flag for "target values outside {0, 1} (∪ ignore_index)".

    The message prefix ("Detected the following values in `target` ...
    expected only ...") deliberately matches the eager/reference wording
    (``stat_scores.py``), so code matching the reference's message pattern
    catches both the eager raise and this deferred one. The offending value
    list itself is necessarily omitted: this check runs fused on-device
    inside the compiled update, where the values cannot be read back without
    the host sync the fused path exists to avoid.
    """
    target = jnp.asarray(target)
    allowed = {0, 1} if ignore_index is None else {0, 1, ignore_index}
    ok = (target == 0) | (target == 1)
    if ignore_index is not None:
        ok = ok | (target == ignore_index)
    msgs = (
        "Detected the following values in `target` outside of the expected set, but expected"
        f" only the following values {sorted(allowed)} (offending value list omitted: check"
        " ran fused on-device).",
    )
    return msgs, jnp.any(~ok)[None]


def _no_value_flags(*_args: Array, **_kwargs: Array):
    """For metrics whose validation is metadata-only (checked at trace time):
    no value checks to fuse, compiled ``validate_args=True`` updates are
    unconditionally safe."""
    return (), jnp.zeros((0,), dtype=jnp.bool_)


def check_forward_full_state_property(
    metric_class,
    init_args: Optional[dict] = None,
    input_args: Optional[dict] = None,
    num_update_to_compare: Sequence[int] = (10, 100, 1000),
    reps: int = 5,
) -> None:
    """Check whether ``full_state_update=False`` is safe for a metric class.

    Reference ``utilities/checks.py:636``: runs ``forward`` under both the
    conservative double-update path (``full_state_update=True``) and the fast
    single-update path, verifies the batch values agree, then reports timing
    for each so authors can pick the flag with evidence.
    """
    import time as _time

    import jax as _jax

    init_args = init_args or {}
    input_args = input_args or {}

    class FullState(metric_class):
        full_state_update = True

    class PartState(metric_class):
        full_state_update = False

    full_state = FullState(**init_args)
    part_state = PartState(**init_args)
    equal = True
    for _ in range(num_update_to_compare[0]):
        out1 = full_state(**input_args)
        out2 = part_state(**input_args)
        equal = equal and _jax.tree_util.tree_all(
            _jax.tree_util.tree_map(lambda a, b: bool(jnp.allclose(a, b)), out1, out2)
        )
    res1 = full_state.compute()
    res2 = part_state.compute()
    equal = equal and _jax.tree_util.tree_all(
        _jax.tree_util.tree_map(lambda a, b: bool(jnp.allclose(a, b)), res1, res2)
    )
    if not equal:
        rank_zero_print(
            "Full state and reduced state did not match; recommended setting `full_state_update=True`."
        )
        return

    for metric, name in ((full_state, "Full"), (part_state, "Partial")):
        for num in num_update_to_compare:
            metric.reset()
            start = _time.perf_counter()
            for _ in range(reps):
                for _ in range(num):
                    metric(**input_args)
            end = _time.perf_counter()
            rank_zero_print(f"{name} state for {num} steps took: {(end - start) / reps}")
    rank_zero_print("Recommended setting `full_state_update=False`")
