"""Input validation helpers (host-side, run outside jit).

Parity target: reference ``torchmetrics/utilities/checks.py:33-296``. Validation
inspects *static* properties (shape, dtype, rank) wherever possible so it can
run on traced values; value-dependent checks (label ranges, prob bounds) pull to
host and are therefore only executed on concrete arrays — they are skipped
automatically under jit, matching the ``validate_args=False`` fast path of the
reference.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _is_concrete(x) -> bool:
    """True when ``x`` holds real values (not a tracer) so host checks can run."""
    return not isinstance(x, jax.core.Tracer)


def _check_same_shape(preds: Array, target: Array) -> None:
    """Raise if shapes differ (reference ``checks.py:33-39``)."""
    if preds.shape != target.shape:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, but got {preds.shape} and {target.shape}."
        )


def _is_floating(x: Array) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def _check_valid_prob_values(x: Array, name: str = "preds") -> None:
    if _is_concrete(x) and ((np.asarray(x) < 0).any() or (np.asarray(x) > 1).any()):
        raise ValueError(f"Expected {name} to be probabilities in [0,1], but values outside the range were found.")


def _check_label_range(x: Array, num_classes: int, name: str = "target", allow_ignore: Optional[int] = None) -> None:
    if not _is_concrete(x):
        return
    arr = np.asarray(x)
    if allow_ignore is not None:
        arr = arr[arr != allow_ignore]
    if arr.size and (arr.min() < 0 or arr.max() >= num_classes):
        raise RuntimeError(
            f"Detected more unique values in `{name}` than expected. Expected only {num_classes} but found "
            f"values in range [{arr.min()}, {arr.max()}]."
        )


def _num_samples_check(preds: Array, target: Array) -> None:
    if preds.shape[0] != target.shape[0]:
        raise RuntimeError("Predictions and targets must have the same number of samples.")
