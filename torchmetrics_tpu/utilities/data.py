"""Data-movement helpers: concatenation, one-hot, top-k, bincount.

Parity target: reference ``torchmetrics/utilities/data.py:28-238``. Key TPU
design choice: ``_bincount`` uses the one-hot/segment-sum formulation the
reference itself falls back to under XLA (``utilities/data.py:203-207``) — on
TPU this maps onto the MXU/VPU instead of serialized scatter-adds, so the
"fallback" is actually the fast path.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.utilities.ringbuffer import RingBuffer

Array = jax.Array


def dim_zero_cat(x: Union[Array, List[Array]]) -> Array:
    """Concatenate a (possibly list- or ring-buffer-valued) state along dim 0."""
    if isinstance(x, (jnp.ndarray, np.ndarray)) or hasattr(x, "shape"):
        return x
    if isinstance(x, RingBuffer):
        if not len(x):
            raise ValueError("No samples to concatenate")
        return x.values()
    if not x:  # empty list state
        raise ValueError("No samples to concatenate")
    x = [jnp.atleast_1d(jnp.asarray(el)) for el in x]
    return jnp.concatenate(x, axis=0)


def dim_zero_sum(x: Array) -> Array:
    return jnp.sum(x, axis=0)


def dim_zero_mean(x: Array) -> Array:
    return jnp.mean(x.astype(jnp.float32) if not jnp.issubdtype(x.dtype, jnp.floating) else x, axis=0)


def dim_zero_max(x: Array) -> Array:
    return jnp.max(x, axis=0)


def dim_zero_min(x: Array) -> Array:
    return jnp.min(x, axis=0)


def _flatten(x: Sequence) -> list:
    """Flatten one level of nesting."""
    return [item for sublist in x for item in sublist]


def _flatten_dict(x: dict) -> tuple:
    """Flatten dict-of-dicts one level; returns (flat_dict, duplicates_found)."""
    new_dict = {}
    duplicates = False
    for key, value in x.items():
        if isinstance(value, dict):
            for k, v in value.items():
                if k in new_dict:
                    duplicates = True
                new_dict[k] = v
        else:
            if key in new_dict:
                duplicates = True
            new_dict[key] = value
    return new_dict, duplicates


def to_onehot(label_tensor: Array, num_classes: Optional[int] = None) -> Array:
    """Convert ``(N, ...)`` integer labels into one-hot ``(N, C, ...)``.

    Parity: reference ``utilities/data.py:79-120``; implemented via
    ``jax.nn.one_hot`` (a compare+select XLA kernel, no scatter).
    """
    if num_classes is None:
        num_classes = int(jnp.max(label_tensor)) + 1
    oh = jax.nn.one_hot(label_tensor, num_classes, dtype=jnp.int32)
    # one_hot appends the class axis last; reference wants it at dim 1
    return jnp.moveaxis(oh, -1, 1)


def select_topk(prob_tensor: Array, topk: int = 1, dim: int = 1) -> Array:
    """Binary mask of the top-k entries along ``dim``.

    Parity: reference ``utilities/data.py:123-149``. Uses ``lax.top_k`` (sorted
    network on TPU) + one-hot sum rather than scatter.
    """
    if topk == 1:  # cheap argmax path
        idx = jnp.argmax(prob_tensor, axis=dim, keepdims=True)
        mask = jnp.zeros_like(prob_tensor, dtype=jnp.int32)
        return jnp.put_along_axis(mask, idx, 1, axis=dim, inplace=False)
    moved = jnp.moveaxis(prob_tensor, dim, -1)
    _, idx = jax.lax.top_k(moved, topk)
    oh = jax.nn.one_hot(idx, moved.shape[-1], dtype=jnp.int32).sum(axis=-2)
    return jnp.moveaxis(oh, -1, dim)


def to_categorical(x: Array, argmax_dim: int = 1) -> Array:
    """Probabilities → class index via argmax (reference ``data.py:152-170``)."""
    return jnp.argmax(x, axis=argmax_dim)


def _bincount(x: Array, minlength: Optional[int] = None) -> Array:
    """Static-shape bincount.

    On XLA, ``jnp.bincount`` requires a static ``length``; when ``minlength`` is
    known we use the segment-sum formulation (reference's own XLA fallback at
    ``utilities/data.py:203-207`` — here it is the primary path). With unknown
    length we fall back to host computation (only used outside jit).
    """
    if minlength is None:
        minlength = int(jnp.max(x)) + 1 if x.size else 1
    return jnp.bincount(jnp.ravel(x), length=minlength)


def _flexible_bincount(x: Array) -> Array:
    """Count occurrences of each *unique* value (host-side; dynamic output shape)."""
    x = x - jnp.min(x)
    unique_vals = jnp.unique(x)
    counts = _bincount(x, minlength=int(jnp.max(x)) + 1)
    return counts[unique_vals]


def _cumsum(x: Array, axis: Optional[int] = None, dtype=None) -> Array:
    """Cumulative sum — deterministic on TPU by construction (no atomics)."""
    return jnp.cumsum(x, axis=axis, dtype=dtype)


def concrete_or_none(x):
    """``x`` when it is a host value or concrete array, ``None`` under trace.

    The bridge between value-dependent host logic (validation raises,
    warnings, degenerate-case warnings) and traced execution: callers run
    the host-only branch when this returns non-None and a branchless
    ``jnp.where`` formulation otherwise. The trace-safety analyzer treats
    this as a sanitizer — branching on the result never host-syncs a tracer
    (rules R2/R3 in ANALYSIS.md).

    NOTE: callers must keep any math on the returned value in numpy/python —
    inside an active trace every jnp op returns a tracer even on concrete
    operands (omnistaging).
    """
    from torchmetrics_tpu.utilities.checks import _is_concrete

    return x if _is_concrete(x) else None


def allclose(a: Array, b: Array, rtol: float = 1e-5, atol: float = 1e-8) -> bool:
    """Shape-then-value closeness used by compute-group detection."""
    if a.shape != b.shape:
        return False
    return bool(jnp.allclose(a, b, rtol=rtol, atol=atol))


def _bucket_size(n: int, minimum: int = 8) -> int:
    """Round ``n`` up to the next power of two (>= ``minimum``).

    Static-shape bucketing for jit: padding dynamic extents to power-of-two
    buckets bounds the number of distinct compiled programs.
    """
    b = minimum
    while b < n:
        b *= 2
    return b
