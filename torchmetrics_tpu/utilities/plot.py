"""Plotting helpers (host-side, matplotlib optional).

Parity target: reference ``torchmetrics/utilities/plot.py:62,270``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from torchmetrics_tpu.utilities.imports import _MATPLOTLIB_AVAILABLE

_error_msg = "matplotlib is required to plot metrics. Install it to use `.plot()`."


def _get_ax(ax: Optional[Any] = None) -> Tuple[Any, Any]:
    import matplotlib.pyplot as plt

    if ax is None:
        fig, ax = plt.subplots()
    else:
        fig = ax.get_figure()
    return fig, ax


def plot_single_or_multi_val(
    val: Union[Any, Sequence[Any], Dict[str, Any]],
    ax: Optional[Any] = None,
    higher_is_better: Optional[bool] = None,
    lower_bound: Optional[float] = None,
    upper_bound: Optional[float] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
) -> Tuple[Any, Any]:
    """Plot a scalar, per-class vector, dict of values, or a sequence over steps."""
    if not _MATPLOTLIB_AVAILABLE:
        raise ModuleNotFoundError(_error_msg)
    fig, ax = _get_ax(ax)

    def _np(x: Any) -> np.ndarray:
        return np.asarray(x)

    if isinstance(val, dict):
        for i, (k, v) in enumerate(val.items()):
            arr = _np(v)
            if arr.ndim == 0:
                ax.plot([i], [float(arr)], "o", label=k)
            else:
                ax.plot(arr, label=k)
        ax.legend()
    elif isinstance(val, (list, tuple)) and not hasattr(val, "shape"):
        arr = np.stack([_np(v) for v in val])
        ax.plot(arr, marker="o")
    else:
        arr = _np(val)
        if arr.ndim == 0:
            ax.plot([float(arr)], marker="o")
        else:
            labels = [f"{legend_name or 'class'}_{i}" for i in range(arr.shape[-1])] if arr.ndim == 1 else None
            ax.bar(np.arange(arr.size), arr.ravel(), tick_label=labels)
    if lower_bound is not None or upper_bound is not None:
        ax.set_ylim(lower_bound, upper_bound)
    if name:
        ax.set_title(name)
    return fig, ax


def plot_curve(
    curve: Tuple[Any, Any, Any],
    score: Optional[Any] = None,
    ax: Optional[Any] = None,
    label_names: Optional[Tuple[str, str]] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
) -> Tuple[Any, Any]:
    """Plot an (x, y, thresholds) curve family (ROC / PR curves)."""
    if not _MATPLOTLIB_AVAILABLE:
        raise ModuleNotFoundError(_error_msg)
    fig, ax = _get_ax(ax)
    x, y = np.asarray(curve[0]), np.asarray(curve[1])
    if x.ndim == 1:
        ax.plot(x, y, label=legend_name)
    else:
        for i in range(x.shape[0]):
            ax.plot(x[i], y[i], label=f"{legend_name or 'class'}_{i}")
        ax.legend()
    if label_names:
        ax.set_xlabel(label_names[0])
        ax.set_ylabel(label_names[1])
    if name:
        title = name if score is None else f"{name} ({float(np.asarray(score)):.3f})"
        ax.set_title(title)
    return fig, ax
