"""Plotting helpers (host-side, matplotlib optional).

Parity target: reference ``torchmetrics/utilities/plot.py`` — scalar/series
plotting with bound lines and optimal-value annotation (``:62``), confusion
matrix heatmaps (``:199``), and (x, y, thresholds) curve plotting (``:270``).
"""

from __future__ import annotations

from math import ceil, floor, sqrt
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from torchmetrics_tpu.utilities.imports import _MATPLOTLIB_AVAILABLE

_error_msg = "matplotlib is required to plot metrics. Install it to use `.plot()`."


def _get_ax(ax: Optional[Any] = None) -> Tuple[Any, Any]:
    import matplotlib.pyplot as plt

    if ax is None:
        fig, ax = plt.subplots()
    else:
        fig = ax.get_figure()
    return fig, ax


def _get_col_row_split(n: int) -> Tuple[int, int]:
    """Split ``n`` sub-figures into a near-square (rows, cols) grid."""
    nsq = sqrt(n)
    if int(nsq) == nsq:
        return int(nsq), int(nsq)
    if floor(nsq) * ceil(nsq) >= n:
        return floor(nsq), ceil(nsq)
    return ceil(nsq), ceil(nsq)


def trim_axs(axs: Any, nb: int) -> Any:
    """Drop all but the first ``nb`` axes from a subplot grid."""
    if not hasattr(axs, "flat"):
        return axs
    axs = axs.flat
    for ax in axs[nb:]:
        ax.remove()
    return axs[:nb]


def plot_single_or_multi_val(
    val: Union[Any, Sequence[Any], Dict[str, Any]],
    ax: Optional[Any] = None,
    higher_is_better: Optional[bool] = None,
    lower_bound: Optional[float] = None,
    upper_bound: Optional[float] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
) -> Tuple[Any, Any]:
    """Plot a scalar, per-class vector, dict of values, or a step sequence,
    with dashed bound lines and an optimal-value marker like the reference."""
    if not _MATPLOTLIB_AVAILABLE:
        raise ModuleNotFoundError(_error_msg)
    fig, ax = _get_ax(ax)

    def _np(x: Any) -> np.ndarray:
        return np.asarray(x)

    if isinstance(val, dict):
        for i, (k, v) in enumerate(val.items()):
            arr = _np(v)
            if arr.ndim == 0:
                ax.plot([i], [float(arr)], "o", markersize=10, label=k)
            else:
                ax.plot(arr, marker="o", markersize=10, linestyle="-", label=k)
                ax.set_xlabel("Step")
    elif isinstance(val, (list, tuple)) and not hasattr(val, "shape"):
        if len(val) and isinstance(val[0], dict):
            series = {k: np.stack([_np(v[k]) for v in val]) for k in val[0]}
            for k, v in series.items():
                ax.plot(v, marker="o", markersize=10, linestyle="-", label=k)
        else:
            arr = np.stack([_np(v) for v in val])
            cols = arr.T if arr.ndim != 1 else arr[None, :]
            multi = arr.ndim != 1
            for i, v in enumerate(cols):
                label = (f"{legend_name} {i}" if legend_name else f"{i}") if multi else ""
                ax.plot(v, marker="o", markersize=10, linestyle="-", label=label)
        ax.set_xlabel("Step")
    else:
        arr = _np(val)
        if arr.ndim == 0:
            ax.plot([float(arr)], marker="o", markersize=10)
        else:
            labels = [f"{legend_name or 'class'}_{i}" for i in range(arr.shape[-1])] if arr.ndim == 1 else None
            ax.bar(np.arange(arr.size), arr.ravel(), tick_label=labels)

    handles, labels = ax.get_legend_handles_labels()
    if handles and labels:
        ax.legend(handles, labels, loc="upper center", bbox_to_anchor=(0.5, 1.15), ncol=3, fancybox=True, shadow=True)

    # bound lines + optimal-value annotation (reference plot.py:140-168)
    ylim = ax.get_ylim()
    if lower_bound is not None and upper_bound is not None:
        factor = 0.1 * (upper_bound - lower_bound)
    else:
        factor = 0.1 * (ylim[1] - ylim[0])
    ax.set_ylim(
        bottom=lower_bound - factor if lower_bound is not None else ylim[0] - factor,
        top=upper_bound + factor if upper_bound is not None else ylim[1] + factor,
    )
    ax.grid(True)
    if name:
        ax.set_ylabel(name)

    xlim = ax.get_xlim()
    xfactor = 0.1 * (xlim[1] - xlim[0])
    y_lines: List[float] = []
    if lower_bound is not None:
        y_lines.append(lower_bound)
    if upper_bound is not None:
        y_lines.append(upper_bound)
    if y_lines:
        ax.hlines(y_lines, xlim[0], xlim[1], linestyles="dashed", colors="k")
    if higher_is_better is not None:
        if lower_bound is not None and not higher_is_better:
            ax.set_xlim(xlim[0] - xfactor, xlim[1])
            ax.text(xlim[0], lower_bound, s="Optimal \n value", horizontalalignment="center", verticalalignment="center")
        if upper_bound is not None and higher_is_better:
            ax.set_xlim(xlim[0] - xfactor, xlim[1])
            ax.text(xlim[0], upper_bound, s="Optimal \n value", horizontalalignment="center", verticalalignment="center")
    return fig, ax


def plot_confusion_matrix(
    confmat: Any,
    ax: Optional[Any] = None,
    add_text: bool = True,
    labels: Optional[List[Union[int, str]]] = None,
    cmap: Optional[Any] = None,
) -> Tuple[Any, Any]:
    """Heatmap(s) for a confusion matrix — (C, C) or multilabel (N, 2, 2)
    grids (reference ``plot.py:199``)."""
    if not _MATPLOTLIB_AVAILABLE:
        raise ModuleNotFoundError(_error_msg)
    import matplotlib.pyplot as plt

    confmat = np.asarray(confmat)
    if confmat.ndim == 3:  # multilabel: one 2x2 panel per label
        nb, n_classes = confmat.shape[0], 2
        if labels is not None and len(labels) != nb:
            raise ValueError(
                "Expected number of elements in arg `labels` to match number of labels in confmat but "
                f"got {len(labels)} and {nb}"
            )
        rows, cols = _get_col_row_split(nb)
        fig, axs = plt.subplots(nrows=rows, ncols=cols)
        axs = np.atleast_1d(np.asarray(axs, dtype=object))
        axs = trim_axs(axs, nb)
    else:
        nb, n_classes = 1, confmat.shape[0]
        fig, axs = _get_ax(ax)
        if labels is not None and len(labels) != n_classes:
            raise ValueError(
                "Expected number of elements in arg `labels` to match number of labels in confmat but "
                f"got {len(labels)} and {n_classes}"
            )
    if confmat.ndim == 3:
        fig_label = labels or np.arange(nb)
        labels = [0, 1]
    else:
        fig_label = None
        labels = labels if labels is not None else np.arange(n_classes).tolist()

    for i in range(nb):
        axis = axs[i] if confmat.ndim == 3 else axs
        mat = confmat[i] if confmat.ndim == 3 else confmat
        axis.imshow(mat, cmap=cmap)
        if fig_label is not None:
            axis.set_title(f"Label {fig_label[i]}", fontsize=15)
        axis.set_xlabel("Predicted class", fontsize=15)
        axis.set_ylabel("True class", fontsize=15)
        axis.set_xticks(np.arange(len(labels)))
        axis.set_yticks(np.arange(len(labels)))
        axis.set_xticklabels(labels, rotation=45, fontsize=10)
        axis.set_yticklabels(labels, rotation=25, fontsize=10)
        if add_text:
            for ii in range(len(labels)):
                for jj in range(len(labels)):
                    axis.text(jj, ii, str(round(float(mat[ii, jj]), 2)), ha="center", va="center", fontsize=15)
    return fig, axs


def plot_curve(
    curve: Tuple[Any, Any, Any],
    score: Optional[Any] = None,
    ax: Optional[Any] = None,
    label_names: Optional[Tuple[str, str]] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
) -> Tuple[Any, Any]:
    """Plot an (x, y, thresholds) curve family (ROC / PR curves)."""
    if not _MATPLOTLIB_AVAILABLE:
        raise ModuleNotFoundError(_error_msg)
    fig, ax = _get_ax(ax)
    if isinstance(curve[0], (list, tuple)):  # ragged per-class curves (thresholds=None)
        xs = [np.asarray(c) for c in curve[0]]
        ys = [np.asarray(c) for c in curve[1]]
        for i, (xi, yi) in enumerate(zip(xs, ys)):
            label = f"{legend_name or 'class'}_{i}"
            if score is not None and np.asarray(score).ndim == 1:
                label += f" AUC={float(np.asarray(score)[i]):0.3f}"
            ax.plot(xi, yi, label=label)
    else:
        x, y = np.asarray(curve[0]), np.asarray(curve[1])
        if x.ndim == 1:
            label = f"AUC={float(np.asarray(score)):0.3f}" if score is not None else legend_name
            ax.plot(x, y, linestyle="-", linewidth=2, label=label)
        else:
            for i in range(x.shape[0]):
                label = f"{legend_name or 'class'}_{i}"
                if score is not None and np.asarray(score).ndim == 1:
                    label += f" AUC={float(np.asarray(score)[i]):0.3f}"
                ax.plot(x[i], y[i], label=label)
    handles, labels = ax.get_legend_handles_labels()
    if handles and labels:
        ax.legend()
    ax.grid(True)
    if label_names:
        ax.set_xlabel(label_names[0])
        ax.set_ylabel(label_names[1])
    if name:
        ax.set_title(name)
    return fig, ax
