"""Utility layer (L1): math, data ops, distributed sync, checks, enums."""

from torchmetrics_tpu.utilities.checks import _check_same_shape, check_forward_full_state_property
from torchmetrics_tpu.utilities.compute import _auc_compute, _safe_divide, _safe_matmul, _safe_xlogy, interp
from torchmetrics_tpu.utilities.data import (
    dim_zero_cat,
    dim_zero_max,
    dim_zero_mean,
    dim_zero_min,
    dim_zero_sum,
    select_topk,
    to_categorical,
    to_onehot,
)
from torchmetrics_tpu.utilities.distributed import class_reduce, gather_all_tensors, reduce, sync_in_jit
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError, TorchMetricsUserWarning
from torchmetrics_tpu.utilities.prints import rank_zero_debug, rank_zero_info, rank_zero_warn
from torchmetrics_tpu.utilities.ringbuffer import RingBuffer, ring_push

__all__ = [
    "_check_same_shape",
    "check_forward_full_state_property",
    "_auc_compute",
    "_safe_divide",
    "_safe_matmul",
    "_safe_xlogy",
    "interp",
    "dim_zero_cat",
    "dim_zero_max",
    "dim_zero_mean",
    "dim_zero_min",
    "dim_zero_sum",
    "select_topk",
    "to_categorical",
    "to_onehot",
    "class_reduce",
    "gather_all_tensors",
    "reduce",
    "RingBuffer",
    "ring_push",
    "sync_in_jit",
    "TorchMetricsUserError",
    "TorchMetricsUserWarning",
    "rank_zero_debug",
    "rank_zero_info",
    "rank_zero_warn",
]
