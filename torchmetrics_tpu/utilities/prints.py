"""Rank-zero-aware printing helpers.

Parity target: reference ``torchmetrics/utilities/prints.py:22-56``. In JAX the
rank is ``jax.process_index()`` rather than the ``LOCAL_RANK`` env var.
"""

from __future__ import annotations

import logging
import warnings
from functools import partial, wraps
from typing import Any, Callable

log = logging.getLogger("torchmetrics_tpu")


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def rank_zero_only(fn: Callable) -> Callable:
    """Call ``fn`` only on process 0."""

    @wraps(fn)
    def wrapped_fn(*args: Any, **kwargs: Any) -> Any:
        if _process_index() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapped_fn


@rank_zero_only
def rank_zero_warn(message: str, category: Any = UserWarning, stacklevel: int = 2, **kwargs: Any) -> None:
    warnings.warn(message, category=category, stacklevel=stacklevel, **kwargs)


@rank_zero_only
def rank_zero_info(message: str, **kwargs: Any) -> None:
    log.info(message, **kwargs)


@rank_zero_only
def rank_zero_debug(message: str, **kwargs: Any) -> None:
    log.debug(message, **kwargs)


def _warn(message: str, **kwargs: Any) -> None:
    warnings.warn(message, stacklevel=3, **kwargs)


_future_warning = partial(_warn, category=FutureWarning)


def _deprecated_root_import_class(name: str, domain: str) -> None:
    _future_warning(
        f"Importing `{name}` from `torchmetrics_tpu` was deprecated; import it from"
        f" `torchmetrics_tpu.{domain}` instead."
    )


def _deprecated_root_import_func(name: str, domain: str) -> None:
    _future_warning(
        f"Importing `{name}` from `torchmetrics_tpu.functional` was deprecated; import it from"
        f" `torchmetrics_tpu.functional.{domain}` instead."
    )
