"""Pickle support for extractor objects holding jit-compiled closures."""

from __future__ import annotations


class PickleableJitMixin:
    """Drop compiled-forward attributes on pickle, rebuild on unpickle.

    Subclasses list their compiled attributes in ``_COMPILED_ATTRS`` and
    implement ``_build_forward()`` (also called at the end of ``__init__``).
    """

    _COMPILED_ATTRS: tuple = ()

    def __getstate__(self):
        return {k: v for k, v in self.__dict__.items() if k not in self._COMPILED_ATTRS}

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._build_forward()
