"""Distributed synchronization backend.

Parity target: reference ``torchmetrics/utilities/distributed.py`` — but built
on JAX collectives instead of ``torch.distributed``:

- **Eager path** (outside jit, multi-host): ``gather_all_tensors`` uses
  ``jax.experimental.multihost_utils.process_allgather`` over DCN — the analogue
  of the reference's NCCL ``all_gather`` (``utilities/distributed.py:97-147``).
  Uneven leading dims are handled with the same pad-to-max-then-trim protocol.
- **In-jit path** (inside ``pjit``/``shard_map``): ``sync_in_jit`` maps each
  state's declared reduction onto a fused XLA collective — ``lax.psum`` /
  ``pmax`` / ``pmin`` for scalarizable reductions (a single ICI all-reduce) and
  ``lax.all_gather`` for cat/None states. This is the TPU-native design: sync is
  *part of the compiled step function*, not an eager epilogue.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

Array = jax.Array


def reduce(x: Array, reduction: Optional[str]) -> Array:
    """Reduce a tensor: ``elementwise_mean``/``sum``/``none`` (reference ``distributed.py:22``)."""
    if reduction == "elementwise_mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    if reduction in ("none", None):
        return x
    raise ValueError("Reduction parameter unknown.")


def class_reduce(
    num: Array, denom: Array, weights: Array, class_reduction: str = "none"
) -> Array:
    """Per-class fraction with micro/macro/weighted/none reduction (reference ``distributed.py:45``)."""
    valid_reduction = ("micro", "macro", "weighted", "none", None)
    fraction = jnp.sum(num) / jnp.sum(denom) if class_reduction == "micro" else num / denom
    fraction = jnp.where(jnp.isnan(fraction), 0.0, fraction)
    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        return jnp.sum(fraction * (weights.astype(jnp.float32) / jnp.sum(weights)))
    if class_reduction in ("none", None):
        return fraction
    raise ValueError(f"Reduction parameter {class_reduction} unknown. Choose between one of these: {valid_reduction}")


# ---------------------------------------------------------------------------
# Eager multi-process gather (DCN / multi-host)
# ---------------------------------------------------------------------------


def distributed_available() -> bool:
    """True when more than one JAX process participates (multi-host)."""
    try:
        return jax.process_count() > 1
    except Exception:
        return False


def gather_all_tensors(result: Array, group: Optional[Any] = None) -> List[Array]:
    """Gather a tensor from all processes, supporting uneven leading dims.

    Single-process: returns ``[result]``. Multi-host: all-gathers via
    ``process_allgather``; tensors with mismatched shapes are padded to the
    per-dim max, gathered, then trimmed back (reference protocol at
    ``utilities/distributed.py:135-147``).
    """
    if not distributed_available():
        return [result]

    from jax.experimental import multihost_utils

    result = jnp.asarray(result)
    local_shape = jnp.asarray(result.shape, dtype=jnp.int32)
    all_shapes = multihost_utils.process_allgather(local_shape)  # (world, ndim)
    import numpy as np

    all_shapes = np.asarray(all_shapes)
    if (all_shapes == all_shapes[0]).all():
        gathered = multihost_utils.process_allgather(result)
        return [jnp.asarray(gathered[i]) for i in range(gathered.shape[0])]

    max_shape = all_shapes.max(axis=0)
    pad = [(0, int(m - s)) for m, s in zip(max_shape, result.shape)]
    padded = jnp.pad(result, pad)
    gathered = multihost_utils.process_allgather(padded)
    out = []
    for i in range(gathered.shape[0]):
        slices = tuple(slice(0, int(d)) for d in all_shapes[i])
        out.append(jnp.asarray(gathered[i])[slices])
    return out


# ---------------------------------------------------------------------------
# In-jit collectives over a named mesh axis (ICI)
# ---------------------------------------------------------------------------

_REDUCE_COLLECTIVES: Dict[str, Callable] = {}


def sync_in_jit(
    state: Dict[str, Array],
    reductions: Dict[str, Union[str, Callable, None]],
    axis_name: str,
) -> Dict[str, Array]:
    """Synchronize a metric-state pytree across a named mesh axis, inside jit.

    Each state key's declared reduction picks the collective:

    - ``"sum"`` → ``lax.psum`` (one fused all-reduce over ICI)
    - ``"mean"`` → ``lax.pmean``
    - ``"max"``/``"min"`` → ``lax.pmax``/``lax.pmin``
    - ``"cat"``/``None`` → ``lax.all_gather`` then flatten the device axis
    - custom callable → all_gather then apply callable on the stacked axis

    Usable directly inside ``shard_map``/``pmap`` bodies — sync fuses into the
    surrounding compiled step (the reference's eager barrier+all_gather protocol
    has no in-graph analogue; this is the TPU-native redesign, SURVEY §2.10).
    """
    out = {}
    for name, value in state.items():
        red = reductions.get(name, "sum")
        if red == "sum":
            out[name] = jax.lax.psum(value, axis_name)
        elif red == "mean":
            out[name] = jax.lax.pmean(value, axis_name)
        elif red == "max":
            out[name] = jax.lax.pmax(value, axis_name)
        elif red == "min":
            out[name] = jax.lax.pmin(value, axis_name)
        elif red == "cat":
            # tiled all_gather concatenates along dim 0 directly: (world*n, ...)
            out[name] = jax.lax.all_gather(value, axis_name, tiled=True)
        elif red is None:
            out[name] = jax.lax.all_gather(value, axis_name)  # stacked (world, ...)
        elif callable(red):
            gathered = jax.lax.all_gather(value, axis_name)
            out[name] = red(gathered)
        else:
            raise ValueError(f"Unknown reduction {red!r} for state {name!r}")
    return out
