"""Distributed synchronization backend.

Parity target: reference ``torchmetrics/utilities/distributed.py`` — but built
on JAX collectives instead of ``torch.distributed``:

- **Eager path** (outside jit, multi-host): ``gather_all_tensors`` uses
  ``jax.experimental.multihost_utils.process_allgather`` over DCN — the analogue
  of the reference's NCCL ``all_gather`` (``utilities/distributed.py:97-147``).
  Uneven leading dims are handled with the same pad-to-max-then-trim protocol.
- **In-jit path** (inside ``pjit``/``shard_map``): ``sync_in_jit`` maps each
  state's declared reduction onto a fused XLA collective — ``lax.psum`` /
  ``pmax`` / ``pmin`` for scalarizable reductions (a single ICI all-reduce) and
  ``lax.all_gather`` for cat/None states. This is the TPU-native design: sync is
  *part of the compiled step function*, not an eager epilogue.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.utilities.ringbuffer import RingBuffer

Array = jax.Array


def shard_map(f: Callable, *, mesh: Any, in_specs: Any, out_specs: Any, check_vma: Optional[bool] = None, **kwargs: Any):
    """Version-portable ``shard_map``.

    jax ≥ 0.6 exposes ``jax.shard_map`` with a ``check_vma`` kwarg; earlier
    releases only have ``jax.experimental.shard_map.shard_map`` whose
    equivalent kwarg is ``check_rep``. Tests and examples import from here so
    the suite collects on either line (the bare ``from jax import shard_map``
    was a hard collection error on 0.4.x).
    """
    try:
        from jax import shard_map as _shard_map  # type: ignore[attr-defined]  # jax >= 0.6

        if check_vma is not None:
            kwargs["check_vma"] = check_vma
    except ImportError:
        from jax.experimental.shard_map import shard_map as _shard_map  # jax <= 0.5

        if check_vma is not None:
            kwargs["check_rep"] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)


def reduce(x: Array, reduction: Optional[str]) -> Array:
    """Reduce a tensor: ``elementwise_mean``/``sum``/``none`` (reference ``distributed.py:22``)."""
    if reduction == "elementwise_mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    if reduction in ("none", None):
        return x
    raise ValueError("Reduction parameter unknown.")


def class_reduce(
    num: Array, denom: Array, weights: Array, class_reduction: str = "none"
) -> Array:
    """Per-class fraction with micro/macro/weighted/none reduction (reference ``distributed.py:45``)."""
    valid_reduction = ("micro", "macro", "weighted", "none", None)
    fraction = jnp.sum(num) / jnp.sum(denom) if class_reduction == "micro" else num / denom
    fraction = jnp.where(jnp.isnan(fraction), 0.0, fraction)
    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        return jnp.sum(fraction * (weights.astype(jnp.float32) / jnp.sum(weights)))
    if class_reduction in ("none", None):
        return fraction
    raise ValueError(f"Reduction parameter {class_reduction} unknown. Choose between one of these: {valid_reduction}")


# ---------------------------------------------------------------------------
# Coordination-service KV namespace + TTL hygiene
# ---------------------------------------------------------------------------

# every key this library writes into the coordination service's KV store
# lives under one namespace, so a shared coordinator (multi-job clusters,
# the fleet aggregation tier) can attribute — and bulk-expire — our keys
# without ever touching another tenant's
KV_NAMESPACE = "tm_tpu"


def kv_key(*parts: Any, namespace: str = KV_NAMESPACE) -> str:
    """Build one namespaced coordination-service KV key.

    Parts are joined with ``/`` under the library namespace; a part that
    itself contains ``/`` (or is empty) is rejected — it would silently
    change the key's depth and break prefix scans (the fleet tier's
    contribution sweep and the TTL janitor both walk keys by prefix).
    """
    if not parts:
        raise ValueError("kv_key needs at least one part")
    rendered = []
    for part in parts:
        text = str(part)
        if not text or "/" in text:
            raise ValueError(f"kv_key part {part!r} must be non-empty and free of '/'")
        rendered.append(text)
    return "/".join([namespace, *rendered])


class KvTtlJanitor:  # concurrency: shared fleet publishers note() while epoch sweeps expire
    """Bounded TTL ledger for KV keys this process published.

    The coordination service retains a key until someone deletes it, so a
    long-running stream that publishes per-epoch keys (the fleet
    aggregation tier, the allgather fallback) must garbage-collect its own
    writes or grow the coordinator's memory without bound. Writers
    :meth:`note` every key they publish; a periodic :meth:`sweep` deletes
    the ones older than ``ttl_s`` through the caller's delete function —
    consumed keys are :meth:`forget`-ed at fold time, so the janitor only
    ever touches keys nobody claimed (dead publishers, orphaned epochs).
    """

    def __init__(self, ttl_s: float = 300.0) -> None:
        if ttl_s <= 0:
            raise ValueError(f"`ttl_s` must be positive, got {ttl_s}")
        self.ttl_s = float(ttl_s)
        import threading

        self._lock = threading.Lock()
        self._born: Dict[str, float] = {}

    def note(self, key: str, now: Optional[float] = None) -> None:
        """Record (or refresh) one published key's birth time."""
        ts = time.monotonic() if now is None else float(now)
        with self._lock:
            self._born[key] = ts

    def forget(self, key: str) -> None:
        """Drop a key from the ledger (it was consumed and deleted by a reader)."""
        with self._lock:
            self._born.pop(key, None)

    def pending(self) -> int:
        with self._lock:
            return len(self._born)

    def sweep(
        self, delete: Callable[[str], Any], now: Optional[float] = None
    ) -> List[str]:
        """Delete every tracked key older than the TTL; return the reaped keys.

        Delete failures (key already consumed by a reader, coordinator
        restart) drop the key from the ledger anyway — the janitor's job is
        bounding coordinator memory, not guaranteeing deletion receipts.
        """
        ts = time.monotonic() if now is None else float(now)
        with self._lock:
            expired = [k for k, born in self._born.items() if ts - born >= self.ttl_s]
            for key in expired:
                del self._born[key]
        for key in expired:
            try:
                delete(key)
            except Exception:  # noqa: BLE001 - best-effort hygiene, never a fault
                pass
        return expired


# ---------------------------------------------------------------------------
# Eager multi-process gather (DCN / multi-host)
# ---------------------------------------------------------------------------


# Transport seam: every eager collective flows through `process_allgather`,
# so the resilience harness (torchmetrics_tpu/_resilience/faultinject.py) can
# simulate worlds, failures, and stalls by patching these two module globals —
# the code path under test stays byte-identical to the real multi-host one.
_world_override: Optional[int] = None  # simulated world size (None = real)
_transport: Optional[Callable[[Any], Any]] = None  # transport override (None = real)

# XLA's process_allgather lowers to a jitted computation over a global mesh,
# which the CPU backend rejects outright ("Multiprocess computations aren't
# implemented on the CPU backend"). Multi-process CPU worlds are exactly what
# tests and local dev clusters run, so the transport falls back to the
# distributed coordination service's KV store — the control-plane channel
# `jax.distributed.initialize` already established. The decision is cached:
# the probe failure is deterministic per backend, so every process flips
# together and collective ordering stays symmetric.
_kv_fallback: Optional[bool] = None
_kv_seq = 0


def _kv_timeout_ms() -> int:
    import os

    try:
        return int(os.environ.get("TM_TPU_KV_GATHER_TIMEOUT_MS", "120000"))
    except ValueError:
        return 120000


def _kv_allgather_leaf(x: Any) -> Any:
    """All-gather one host array through the coordination-service KV store.

    Protocol per call: publish this process's shard bytes under a sequenced
    key, blocking-read every peer's, barrier (so no peer deletes a key
    before everyone read it), then delete own key so a long-running stream
    cannot grow the coordinator's memory without bound. Callers issue
    gathers in the same order on every process (the same property the XLA
    collective needs), so the per-process sequence numbers agree.
    """
    import io

    from jax._src import distributed

    client = distributed.global_state.client
    if client is None:
        raise RuntimeError(
            "KV-store allgather fallback needs jax.distributed.initialize() (no coordination client)"
        )
    global _kv_seq
    seq = _kv_seq
    _kv_seq += 1
    pid, nproc = jax.process_index(), jax.process_count()
    base = kv_key("allgather", seq)
    buf = io.BytesIO()
    np.save(buf, np.asarray(x), allow_pickle=False)
    client.key_value_set_bytes(f"{base}/{pid}", buf.getvalue())
    timeout = _kv_timeout_ms()
    try:
        shards = []
        for i in range(nproc):
            raw = client.blocking_key_value_get_bytes(f"{base}/{i}", timeout)
            shards.append(np.load(io.BytesIO(bytes(raw)), allow_pickle=False))
        client.wait_at_barrier(f"{base}/done", timeout)
    finally:
        # the barrier guarantees no peer still needs our key on the success
        # path; on failure the barrier has coupled every peer into the same
        # failure (they retry with the next sequence number together), so
        # deleting here can strand nobody — and NOT deleting would leak one
        # key into the coordinator per transient fault, forever
        try:
            client.key_value_delete(f"{base}/{pid}")
        except Exception:  # noqa: BLE001 - cleanup must not mask the gather error
            pass
    return np.stack(shards)


def _kv_allgather(x: Any) -> Any:
    return jax.tree_util.tree_map(_kv_allgather_leaf, x)


def _default_transport(x: Any) -> Any:
    global _kv_fallback
    if _kv_fallback:
        return _kv_allgather(x)
    from jax.experimental import multihost_utils

    try:
        out = multihost_utils.process_allgather(x)
    except Exception as err:  # noqa: BLE001 - backend-capability probe
        # the capability error surfaces locally (compile/execute of the
        # jitted gather fails before any cross-process exchange), so falling
        # back here cannot leave peers stranded mid-collective. The message
        # match is the precise signal; the structural condition keeps the
        # fallback alive if a jax upgrade rewords the text — but it must
        # only match the DETERMINISTIC capability error (every process flips
        # together), so it additionally requires the INVALID_ARGUMENT status
        # class: transient per-process faults surface as INTERNAL /
        # RESOURCE_EXHAUSTED, and flipping ONE process to the KV transport
        # while its peers stay on the XLA collective would deadlock both
        structural = (
            type(err).__name__ == "XlaRuntimeError"
            and "INVALID_ARGUMENT" in str(err)
            and jax.default_backend() == "cpu"
            and jax.process_count() > 1
        )
        if "Multiprocess computations aren't implemented" not in str(err) and not structural:
            raise
        _kv_fallback = True
        return _kv_allgather(x)
    _kv_fallback = False
    return out


def process_allgather(x: Any) -> Any:
    """All-gather ``x`` across processes (leading world axis on every leaf)."""
    fn = _transport if _transport is not None else _default_transport
    return fn(x)


def world_size() -> int:
    """Number of participating processes (honors the simulated-world override)."""
    if _world_override is not None:
        return _world_override
    try:
        return jax.process_count()
    except Exception:
        return 1


def distributed_available() -> bool:
    """True when more than one JAX process participates (multi-host)."""
    return world_size() > 1


def gather_all_tensors(result: Array, group: Optional[Any] = None) -> List[Array]:
    """Gather a tensor from all processes, supporting uneven leading dims.

    Single-process: returns ``[result]``. Multi-host: all-gathers via
    ``process_allgather``; tensors with mismatched shapes are padded to the
    per-dim max, gathered, then trimmed back (reference protocol at
    ``utilities/distributed.py:135-147``).

    ``group`` restricts the result to a subset of process indices — the
    analogue of the reference's ``torch.distributed`` group handle. The
    gather itself still spans all processes (JAX's ``process_allgather`` is
    global); members outside the group are dropped from the returned list,
    which is reduction-equivalent to a subgroup collective.
    """
    if not distributed_available():
        return [result]

    result = jnp.asarray(result)
    local_shape = jnp.asarray(result.shape, dtype=jnp.int32)
    all_shapes = process_allgather(local_shape)  # (world, ndim)
    import numpy as np

    all_shapes = np.asarray(all_shapes)
    if group is not None:
        members = [int(i) for i in group]
        if len(set(members)) != len(members):
            raise ValueError(f"`group` must not contain duplicate process indices, got {group}")
        if any(i < 0 or i >= all_shapes.shape[0] for i in members):
            raise ValueError(f"`group` indices {group} out of range for world size {all_shapes.shape[0]}")
    else:
        members = list(range(all_shapes.shape[0]))

    if (all_shapes == all_shapes[0]).all():
        gathered = process_allgather(result)
        return [jnp.asarray(gathered[i]) for i in members]

    max_shape = all_shapes.max(axis=0)
    pad = [(0, int(m - s)) for m, s in zip(max_shape, result.shape)]
    padded = jnp.pad(result, pad)
    gathered = process_allgather(padded)
    out = []
    for i in members:
        slices = tuple(slice(0, int(d)) for d in all_shapes[i])
        out.append(jnp.asarray(gathered[i])[slices])
    return out


# ---------------------------------------------------------------------------
# In-jit collectives over a named mesh axis (ICI)
# ---------------------------------------------------------------------------

_REDUCE_COLLECTIVES: Dict[str, Callable] = {}


def sync_in_jit(
    state: Dict[str, Array],
    reductions: Dict[str, Union[str, Callable, None]],
    axis_name: str,
    axis_index_groups: Optional[Sequence[Sequence[int]]] = None,
) -> Dict[str, Array]:
    """Synchronize a metric-state pytree across a named mesh axis, inside jit.

    Each state key's declared reduction picks the collective:

    - ``"sum"`` → ``lax.psum`` (one fused all-reduce over ICI)
    - ``"mean"`` → ``lax.pmean``
    - ``"max"``/``"min"`` → ``lax.pmax``/``lax.pmin``
    - ``"cat"``/``None`` → ``lax.all_gather`` then flatten the device axis
    - custom callable → all_gather then apply callable on the stacked axis

    ``axis_index_groups`` partitions the mesh axis into disjoint subgroups —
    the in-jit realization of the reference's ``process_group`` kwarg
    (``metric.py:125``): each subgroup reduces independently, so e.g.
    ``[[0, 1], [2, 3]]`` keeps two independent data-parallel replicas.

    Usable directly inside ``shard_map``/``pmap`` bodies — sync fuses into the
    surrounding compiled step (the reference's eager barrier+all_gather protocol
    has no in-graph analogue; this is the TPU-native redesign, SURVEY §2.10).
    """
    if axis_index_groups is not None:
        member_selector = _grouped_member_selector(axis_name, axis_index_groups)

    out = {}
    for name, value in state.items():
        red = reductions.get(name, "sum")
        if red not in _COLLECTIVES and not callable(red):
            raise ValueError(f"Unknown reduction {red!r} for state {name!r}")
        if isinstance(value, RingBuffer):
            # fixed-capacity cat state: gather storage+mask (static shapes), sum
            # the cursor — result is a world-capacity buffer on every shard
            if red not in ("cat", None):
                raise ValueError(f"RingBuffer state {name!r} requires a 'cat' reduction, got {red!r}")
            data, valid = value.masked()
            if axis_index_groups is None:
                g_data = jax.lax.all_gather(data, axis_name, tiled=True)
                g_valid = jax.lax.all_gather(valid, axis_name, tiled=True)
                g_count = jax.lax.psum(value.count, axis_name)
            else:
                g_data = member_selector(data).reshape(-1, *data.shape[1:])
                g_valid = member_selector(valid).reshape(-1)
                g_count = jnp.sum(member_selector(value.count), axis=0)
            out[name] = type(value)(int(g_data.shape[0]), _data=g_data, _valid=g_valid, _count=g_count)
            continue
        if axis_index_groups is None:
            if callable(red) and red not in _COLLECTIVES:
                out[name] = red(jax.lax.all_gather(value, axis_name))
            else:
                out[name] = _COLLECTIVES[red][0](value, axis_name)
        else:
            # grouped: lax collectives reject axis_index_groups on shard_map's
            # manual axes, so gather the world axis and reduce this shard's
            # (statically known) group rows — XLA folds the selection in
            mine = member_selector(value)  # (group_size, ...)
            if callable(red) and red not in _COLLECTIVES:
                out[name] = red(mine)
            else:
                out[name] = _COLLECTIVES[red][1](mine)
    return out


# reduction kind -> (full-axis collective, within-group local reduction over
# the gathered leading axis). Both sides of every kind live on one row so the
# grouped and ungrouped paths cannot drift apart.
_COLLECTIVES: Dict[Any, Any] = {
    "sum": (lambda v, ax: jax.lax.psum(v, ax), lambda m: jnp.sum(m, axis=0)),
    "mean": (lambda v, ax: jax.lax.pmean(v, ax), lambda m: jnp.mean(m, axis=0)),
    "max": (lambda v, ax: jax.lax.pmax(v, ax), lambda m: jnp.max(m, axis=0)),
    "min": (lambda v, ax: jax.lax.pmin(v, ax), lambda m: jnp.min(m, axis=0)),
    "cat": (
        lambda v, ax: jax.lax.all_gather(v, ax, tiled=True),
        lambda m: m.reshape(m.shape[0] * m.shape[1], *m.shape[2:]),
    ),
    None: (lambda v, ax: jax.lax.all_gather(v, ax), lambda m: m),
}


def validate_axis_groups(groups: Sequence[Sequence[int]], world: Optional[int] = None) -> None:
    """The `axis_index_groups` invariant, in ONE place: equal-sized disjoint
    subgroups partitioning ``0..world-1`` (the same constraints the native
    primitives have). ``world`` defaults to the total membership; callers who
    know their axis size pass it so a wrong-sized partition fails too. Both
    the in-jit grouped selector and the SPMD engine's eager construction
    check call this — the invariant cannot drift between them."""
    sizes = {len(g) for g in groups}
    if len(sizes) != 1:
        raise ValueError(f"All `axis_index_groups` must have the same size, got sizes {sorted(sizes)}")
    expected = sum(len(g) for g in groups) if world is None else world
    seen = sorted(i for g in groups for i in g)
    if seen != list(range(expected)):
        raise ValueError(f"`axis_index_groups` must partition 0..{expected - 1}, got {groups}")


def _grouped_member_selector(axis_name: str, groups: Sequence[Sequence[int]]) -> Callable[[Array], Array]:
    """Build ``value -> (group_size, ...)`` selecting this shard's group rows
    from a full all_gather. Groups must be equal-sized and partition the axis
    (the same constraints the native ``axis_index_groups`` primitives have)."""
    validate_axis_groups(groups)
    world = sum(len(g) for g in groups)

    group_of = [0] * world
    for gid, g in enumerate(groups):
        for rank in g:
            group_of[rank] = gid
    group_of_arr = jnp.asarray(group_of)
    members_arr = jnp.asarray([list(g) for g in groups])  # (n_groups, group_size)

    def select(value: Array) -> Array:
        idx = jax.lax.axis_index(axis_name)
        my_members = members_arr[group_of_arr[idx]]
        gathered = jax.lax.all_gather(value, axis_name)  # (world, ...)
        return gathered[my_members]

    return select
