"""String-valued enums used across the framework.

Parity target: reference ``torchmetrics/utilities/enums.py:20-150``.
"""

from __future__ import annotations

from enum import Enum
from typing import Optional


class EnumStr(str, Enum):
    """Base class: case/sep-insensitive string enum with a helpful error."""

    @staticmethod
    def _name() -> str:
        return "Task"

    @classmethod
    def from_str(cls, value: str, source: str = "key") -> "EnumStr":
        try:
            norm = value.replace("-", "_").replace(" ", "_").lower()
            for member in cls:
                if member.value.replace("-", "_").replace(" ", "_").lower() == norm or member.name.lower() == norm:
                    return member
            raise KeyError(value)
        except (KeyError, AttributeError):
            valid = [m.lower() for m in cls.__members__]
            raise ValueError(
                f"Invalid {cls._name()}: expected one of {valid}, but got {value}."
            ) from None

    def __str__(self) -> str:
        return self.value.lower()

    def __eq__(self, other: object) -> bool:
        if isinstance(other, str):
            norm = lambda s: s.replace("-", "_").replace(" ", "_").lower()  # noqa: E731
            return norm(self.value) == norm(other)
        return super().__eq__(other)

    def __hash__(self) -> int:
        return hash(self.value.replace("-", "_").replace(" ", "_").lower())


class DataType(EnumStr):
    """Type of an input tensor."""

    BINARY = "binary"
    MULTILABEL = "multi-label"
    MULTICLASS = "multi-class"
    MULTIDIM_MULTICLASS = "multi-dim multi-class"

    @staticmethod
    def _name() -> str:
        return "Data type"


class AverageMethod(EnumStr):
    """Reduction applied over classes."""

    MICRO = "micro"
    MACRO = "macro"
    WEIGHTED = "weighted"
    NONE = "none"
    SAMPLES = "samples"

    @staticmethod
    def _name() -> str:
        return "Average method"


class MDMCAverageMethod(EnumStr):
    """Reduction for multi-dim multi-class inputs."""

    GLOBAL = "global"
    SAMPLEWISE = "samplewise"


class ClassificationTask(EnumStr):
    """Classification task dispatch: binary / multiclass / multilabel."""

    BINARY = "binary"
    MULTICLASS = "multiclass"
    MULTILABEL = "multilabel"

    @staticmethod
    def _name() -> str:
        return "Classification"


class ClassificationTaskNoBinary(EnumStr):
    MULTICLASS = "multiclass"
    MULTILABEL = "multilabel"

    @staticmethod
    def _name() -> str:
        return "Classification"


class ClassificationTaskNoMultilabel(EnumStr):
    BINARY = "binary"
    MULTICLASS = "multiclass"

    @staticmethod
    def _name() -> str:
        return "Classification"


def _check_task(task: str, enum_cls: type = ClassificationTask) -> EnumStr:
    return enum_cls.from_str(task) if isinstance(task, str) else task
