"""Typed exceptions for the TPU metrics framework.

API-parity with reference ``torchmetrics/utilities/exceptions.py``.
"""


class TorchMetricsUserError(Exception):
    """Error raised on wrong usage of the metrics API."""


class TorchMetricsUserWarning(UserWarning):
    """Warning raised on questionable usage of the metrics API."""
