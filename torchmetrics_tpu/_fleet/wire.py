"""Fleet wire payloads: integrity-checked metric state as the transport format.

One contribution = one metric's per-epoch state delta, serialized as

.. code-block:: text

    TMFLEET1\\n                  magic
    <32-byte sha256(payload)>    outer checksum (transport corruption fence)
    <payload>                    pickled contribution record

The pickled record carries the states exactly as
``Metric.state_dict(integrity=True, all_states=True)`` produced them —
including the per-state ``#integrity`` block — plus the epoch fence
coordinates (``node``, ``epoch``), the journaled update count the merge
operator needs for correct mean weighting, and leaf-level *provenance*
(which ``(leaf, epoch)`` deltas were folded into this contribution), so a
global rollup can name exactly which edge contributions it contains.

Two independent verification layers per hop, by design:

1. the **outer checksum** rejects transport-mangled bytes before pickle
   ever runs (a bit-flipped pickle stream can raise anything — or worse,
   load);
2. the **integrity block** travels inside and is re-verified at *fold*
   time through ``load_state_dict(strict="repair")`` on a scratch clone —
   a corrupt state quarantines the whole contribution instead of folding
   a silently-repaired (wrong) value into the rollup.
"""

from __future__ import annotations

import hashlib
import pickle
import time
from dataclasses import dataclass
from typing import Any, Dict, Tuple

__all__ = [
    "WIRE_VERSION",
    "WIRE_MAGIC",
    "Contribution",
    "encode_contribution",
    "decode_contribution",
    "CorruptContribution",
]

WIRE_VERSION = 1
WIRE_MAGIC = b"TMFLEET1\n"
_SHA_BYTES = 32


class CorruptContribution(ValueError):
    """A contribution failed outer-envelope verification (quarantine, don't fold)."""


@dataclass(frozen=True)
class Contribution:
    """One decoded, envelope-verified contribution (integrity block unverified yet)."""

    node: str
    epoch: int
    count: int
    metric_class: str
    states: Dict[str, Any]
    sources: Tuple[Tuple[str, int], ...]
    published_at: float
    digest: str

    @property
    def age_ms(self) -> float:
        return max(0.0, (time.time() - self.published_at) * 1000.0)


def encode_contribution(
    metric: Any,
    node: str,
    epoch: int,
    sources: Tuple[Tuple[str, int], ...],
) -> Tuple[bytes, str]:
    """Serialize one metric's current state as a wire contribution.

    Returns ``(blob, digest)`` where ``digest`` is the state-digest
    component of the contribution key — sha256 over the payload, so two
    different states for the same ``(node, epoch)`` (a zombie's stale
    replay vs the live replica) can never collide onto one key.
    """
    record = {
        "version": WIRE_VERSION,
        "node": str(node),
        "epoch": int(epoch),
        "count": int(metric._update_count),
        "class": type(metric).__name__,
        "states": metric.state_dict(integrity=True, all_states=True),
        "sources": tuple((str(n), int(e)) for n, e in sources),
        "published_at": time.time(),
    }
    payload = pickle.dumps(record, protocol=pickle.HIGHEST_PROTOCOL)
    sha = hashlib.sha256(payload).digest()
    return WIRE_MAGIC + sha + payload, sha.hex()[:16]


def decode_contribution(blob: bytes) -> Contribution:
    """Verify the outer envelope and unpickle; raise :class:`CorruptContribution`.

    The checksum is verified BEFORE pickle touches the payload: a corrupt
    pickle stream fails unpredictably, and the quarantine path needs one
    deterministic, attributable error per corrupt payload.
    """
    if not blob.startswith(WIRE_MAGIC):
        raise CorruptContribution("bad magic (not a fleet contribution)")
    body = blob[len(WIRE_MAGIC):]
    if len(body) < _SHA_BYTES:
        raise CorruptContribution("truncated envelope (missing checksum)")
    sha, payload = body[:_SHA_BYTES], body[_SHA_BYTES:]
    if hashlib.sha256(payload).digest() != sha:
        raise CorruptContribution("payload checksum mismatch (corrupt in transit)")
    try:
        record = pickle.loads(payload)
    except Exception as err:  # noqa: BLE001 - checksum passed but content unloadable
        raise CorruptContribution(f"payload unpicklable: {type(err).__name__}: {err}") from err
    if not isinstance(record, dict) or record.get("version") != WIRE_VERSION:
        raise CorruptContribution(
            f"unsupported wire version {record.get('version') if isinstance(record, dict) else '?'}"
        )
    try:
        return Contribution(
            node=str(record["node"]),
            epoch=int(record["epoch"]),
            count=int(record["count"]),
            metric_class=str(record["class"]),
            states=dict(record["states"]),
            sources=tuple((str(n), int(e)) for n, e in record["sources"]),
            published_at=float(record["published_at"]),
            digest=sha.hex()[:16],
        )
    except (KeyError, TypeError, ValueError) as err:
        raise CorruptContribution(f"malformed contribution record: {err}") from err
