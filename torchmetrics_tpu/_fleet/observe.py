"""Fleet observability helpers: bounded ``region=`` label cardinality.

A fleet has thousands of edge nodes; exporting one Prometheus label value
per node is the classic cardinality explosion. The fleet tier therefore
reuses the pool tier's :class:`~torchmetrics_tpu._streams.telemetry.
StreamLabeler` (top-K by volume + ``__overflow__`` bucket) behind a thin
string adapter: regions are named (``"region-eu"``), the labeler speaks
integer ids, so this wrapper owns the name <-> id table and returns the
region *name* while it holds a label slot and the shared overflow bucket
once it loses one.
"""

from __future__ import annotations

from typing import Dict

from torchmetrics_tpu._analysis.locksan import SAN as _SAN
from torchmetrics_tpu._analysis.locksan import check_access as _san_check
from torchmetrics_tpu._analysis.locksan import new_lock as _san_lock
from torchmetrics_tpu._streams.telemetry import OVERFLOW_LABEL, StreamLabeler

__all__ = ["OVERFLOW_LABEL", "RegionLabeler"]


class RegionLabeler:  # concurrency: shared node rollup threads note() while scrapes label()
    """Bounded region-name -> telemetry-label mapping (top-K by volume)."""

    def __init__(self, k: int = 8, rebalance_every: int = 512) -> None:
        self._inner = StreamLabeler(k=k, rebalance_every=rebalance_every)
        self._lock = _san_lock("RegionLabeler._lock")
        # concurrency: shared name->id table guarded-by _lock
        self._ids: Dict[str, int] = {}

    def _id_of(self, region: str) -> int:
        with self._lock:
            if _SAN.enabled:
                _san_check(self, "_ids")
            rid = self._ids.get(region)
            if rid is None:
                rid = self._ids[region] = len(self._ids)
            return rid

    def note(self, region: str, n: int = 1) -> str:
        """Record ``n`` events for the region; return its current label value."""
        label = self._inner.note(self._id_of(str(region)), n)
        return str(region) if label != OVERFLOW_LABEL else OVERFLOW_LABEL

    def label(self, region: str) -> str:
        """Current label value WITHOUT recording an event (scrape path)."""
        with self._lock:
            rid = self._ids.get(str(region))
        if rid is None:
            return OVERFLOW_LABEL
        inner = self._inner.label(rid)
        return str(region) if inner != OVERFLOW_LABEL else OVERFLOW_LABEL
