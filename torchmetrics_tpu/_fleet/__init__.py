"""Fault-tolerant hierarchical fleet aggregation: edge -> region -> global.

This package rolls per-host metric state up an N-level tree over a
key-value rendezvous transport, with the failure semantics a fleet
actually needs (FLEET.md):

- **straggler degradation** — per-level fan-in deadlines; children that
  miss the deadline are degraded (partial rollup + ``fleet_partial``
  degradation event + flight dump), never awaited, and their late
  contributions fold into a subsequent epoch;
- **epoch fencing** — contribution keys carry ``(node_id, epoch,
  state_digest)``; the fold ledger plus a sliding watermark turn
  at-least-once delivery into exactly-once folding (zombie replicas
  cannot double-contribute);
- **integrity at every hop** — contributions ship
  ``state_dict(integrity=True)`` behind an outer checksum; corrupt
  payloads are quarantined (``fleet_corrupt``), never folded;
- **guarded publishes** — retries with backoff via the shared
  :class:`~torchmetrics_tpu._resilience.policy.RetryPolicy`; exhausted
  retries retain the delta for the next epoch (``fleet_publish_degraded``).

:mod:`~torchmetrics_tpu._fleet.chaos` composes kills, corruption, KV
faults, stalls, and zombie replays against a 3-level in-process tree and
asserts golden equality over the fenced epochs.
"""

from torchmetrics_tpu._fleet.chaos import (
    FleetChaosResult,
    FleetChaosSpec,
    run_fleet_chaos,
)
from torchmetrics_tpu._fleet.node import AggregationNode, Rollup
from torchmetrics_tpu._fleet.observe import RegionLabeler
from torchmetrics_tpu._fleet.transport import (
    CoordinationServiceKV,
    FleetTransportError,
    InjectedKvFault,
    InProcessKV,
    contribution_key,
    contribution_prefix,
)
from torchmetrics_tpu._fleet.tree import FleetTree
from torchmetrics_tpu._fleet.wire import (
    Contribution,
    CorruptContribution,
    decode_contribution,
    encode_contribution,
)

__all__ = [
    "AggregationNode",
    "Contribution",
    "CoordinationServiceKV",
    "CorruptContribution",
    "FleetChaosResult",
    "FleetChaosSpec",
    "FleetTransportError",
    "FleetTree",
    "InProcessKV",
    "InjectedKvFault",
    "RegionLabeler",
    "Rollup",
    "contribution_key",
    "contribution_prefix",
    "decode_contribution",
    "encode_contribution",
    "run_fleet_chaos",
]
