"""Fleet tree builder + epoch driver: edge -> region -> global in one object.

:meth:`FleetTree.build` wires an N-level tree of
:class:`~torchmetrics_tpu._fleet.node.AggregationNode` over one shared KV
transport: ``branching=(8, 8)`` is the canonical 3-level shape (one global
root, 8 regions, 64 edge leaves). Node ids double as KV key components
(``global``, ``region-03``, ``edge-03-07``), and every node below the root
carries its level-1 ancestor as its ``region=`` telemetry label.

:meth:`FleetTree.run_epoch` drives one fenced epoch through the tree in
fan-in order: leaves publish **asynchronously** (a stalled edge blocks its
own daemon thread, never the driver), then each interior level rolls up
under its fan-in deadline and forwards its delta, then the root rolls up.
``skip`` models dead nodes — a skipped node neither publishes nor rolls
up, which is exactly what its parent's deadline-degrade path is for.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from torchmetrics_tpu._fleet.node import AggregationNode, Rollup
from torchmetrics_tpu._fleet.observe import RegionLabeler
from torchmetrics_tpu._fleet.transport import InProcessKV
from torchmetrics_tpu._resilience.policy import RetryPolicy

__all__ = ["FleetTree"]


class FleetTree:
    """An assembled aggregation tree: ``levels[0]`` is ``[root]``, ``levels[-1]`` the leaves."""

    def __init__(self, levels: List[List[AggregationNode]], kv: InProcessKV, namespace: str) -> None:
        if not levels or len(levels[0]) != 1:
            raise ValueError("FleetTree needs levels with exactly one root")
        self.levels = levels
        self.kv = kv
        self.namespace = namespace
        self.nodes: Dict[str, AggregationNode] = {
            n.node_id: n for level in levels for n in level
        }

    @property
    def root(self) -> AggregationNode:
        return self.levels[0][0]

    @property
    def leaves(self) -> List[AggregationNode]:
        return self.levels[-1]

    @classmethod
    def build(
        cls,
        template,
        branching: Sequence[int] = (8, 8),
        *,
        kv: Optional[InProcessKV] = None,
        namespace: str = "default",
        deadline_s: float = 2.0,
        retry: Optional[RetryPolicy] = None,
        epoch_window: int = 4,
        labeler: Optional[RegionLabeler] = None,
    ) -> "FleetTree":
        """Build an ``len(branching)+1``-level tree with the given fan-outs."""
        if not branching or any(int(b) < 1 for b in branching):
            raise ValueError(f"branching must be non-empty positive fan-outs, got {branching!r}")
        kv = kv if kv is not None else InProcessKV()
        labeler = labeler if labeler is not None else RegionLabeler()

        # ids first, top-down: a parent's ctor needs its children's names
        id_levels: List[List[Tuple[str, str]]] = [[("global", "global")]]  # (node_id, region)
        for depth, fan in enumerate(branching):
            nxt: List[Tuple[str, str]] = []
            for parent_id, parent_region in id_levels[-1]:
                for i in range(int(fan)):
                    if depth == 0:
                        nid = f"region-{i:02d}"
                        region = nid
                    else:
                        suffix = parent_id.split("-", 1)[1] if "-" in parent_id else parent_id
                        nid = f"{'edge' if depth == len(branching) - 1 else 'zone'}-{suffix}-{i:02d}"
                        region = parent_region
                    nxt.append((nid, region))
            id_levels.append(nxt)

        children_of: Dict[str, List[str]] = {}
        for depth in range(len(id_levels) - 1):
            fan = int(branching[depth])
            parents = id_levels[depth]
            kids = id_levels[depth + 1]
            for p_idx, (parent_id, _) in enumerate(parents):
                children_of[parent_id] = [nid for nid, _ in kids[p_idx * fan:(p_idx + 1) * fan]]

        levels: List[List[AggregationNode]] = []
        for depth, level_ids in enumerate(id_levels):
            level_nodes = [
                AggregationNode(
                    nid,
                    template,
                    kv,
                    children=children_of.get(nid, ()),
                    namespace=namespace,
                    region=region,
                    deadline_s=deadline_s,
                    retry=retry,
                    epoch_window=epoch_window,
                    labeler=labeler,
                )
                for nid, region in level_ids
            ]
            levels.append(level_nodes)
        return cls(levels, kv, namespace)

    # ------------------------------------------------------------------ drive
    def run_epoch(self, epoch: int, *, skip: Iterable[str] = ()) -> Rollup:
        """Drive one fenced epoch bottom-up; returns the root's rollup receipt.

        Nodes named in ``skip`` are treated as dead for this epoch: they do
        not publish (leaves) or roll up (interior), and their parents
        degrade to partial rollups at the fan-in deadline.
        """
        dead: Set[str] = {str(s) for s in skip}
        for leaf in self.leaves:
            if leaf.node_id not in dead:
                leaf.publish_async(epoch)
        # interior levels bottom-up, root excluded
        for level in reversed(self.levels[1:-1]):
            for node in level:
                if node.node_id in dead:
                    continue
                node.rollup(epoch)
                node.publish_async(epoch)
        return self.root.rollup(epoch)

    def join_pending(self, timeout: Optional[float] = None) -> None:
        """Drain all in-flight publish threads (test teardown / shutdown)."""
        for node in self.nodes.values():
            node.join_pending(timeout)

    def sweep_expired(self) -> List[str]:
        """TTL-reap orphaned contribution keys from the shared transport."""
        return self.kv.sweep_expired()
