"""Fleet KV transport: the rendezvous layer of the aggregation tree.

The fleet tier moves metric state between processes through a key-value
rendezvous — the same coordination-service channel the eager allgather
fallback already uses (``utilities/distributed.py``), but with a
*directory* access pattern: children publish contributions under
namespaced keys carrying ``(node_id, epoch, state_digest)`` and parents
sweep their children's prefixes. Two implementations share that contract:

- :class:`InProcessKV` — a condition-variable KV store for in-process
  trees (tests, chaos schedules, single-host fleets). It is also the
  fault-injection seam: :meth:`InProcessKV.fail_publishes` raises
  transient errors on the next N ``set`` calls (exercising the guarded
  retry path) and :meth:`InProcessKV.stall_publishes` delays them
  (manufacturing stragglers without sleeping in test code).
- :class:`CoordinationServiceKV` — a thin adapter over the JAX
  distributed coordination client (``key_value_set_bytes`` /
  ``key_value_dir_get`` / ``key_value_delete``), for real multi-host
  fleets that already ran ``jax.distributed.initialize``.

Both note every published key into a
:class:`~torchmetrics_tpu.utilities.distributed.KvTtlJanitor` so orphaned
contributions (dead children, abandoned epochs) are reaped instead of
accumulating in the coordinator forever.
"""

from __future__ import annotations

import fnmatch
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from torchmetrics_tpu._analysis.locksan import SAN as _SAN
from torchmetrics_tpu._analysis.locksan import check_access as _san_check
from torchmetrics_tpu.utilities.distributed import KvTtlJanitor, kv_key

__all__ = [
    "FleetTransportError",
    "InjectedKvFault",
    "InProcessKV",
    "CoordinationServiceKV",
    "contribution_key",
    "contribution_prefix",
]


class FleetTransportError(RuntimeError):
    """A (retryable) transport fault while talking to the fleet KV store."""


class InjectedKvFault(FleetTransportError):
    """Transient KV fault injected by the chaos harness."""


def contribution_key(namespace: str, node_id: str, epoch: int, digest: str) -> str:
    """Key one contribution publishes under: ``(node_id, epoch, state_digest)``.

    The digest in the key is the epoch fence's third coordinate: an
    at-least-once redelivery of the *same* payload lands on the same key
    (idempotent overwrite), while a zombie replica pushing *different*
    state for an already-folded epoch shows up as a second key under the
    same ``(node, epoch)`` prefix — visible, countable, and droppable.
    """
    return kv_key("fleet", namespace, "contrib", node_id, int(epoch), digest)


def contribution_prefix(namespace: str, node_id: str, epoch: int) -> str:
    """Prefix a parent sweeps to find one child's contributions for one epoch."""
    return kv_key("fleet", namespace, "contrib", node_id, int(epoch)) + "/"


class InProcessKV:  # concurrency: shared child publisher threads set() while parents sweep
    """Blocking, fault-injectable KV store for in-process fleet trees.

    One condition variable covers the data dict and the injection
    counters: publishers notify waiters on every ``set``, so a parent's
    deadline wait wakes exactly when a child's contribution lands instead
    of polling.
    """

    def __init__(self, ttl_s: float = 300.0) -> None:
        self._cond = threading.Condition()
        self._data: Dict[str, bytes] = {}
        self.janitor = KvTtlJanitor(ttl_s=ttl_s)
        # fault injection (chaos seam): counters guarded by _cond's lock
        self._fail_next = 0
        self._fail_exc: Callable[[], Exception] = lambda: InjectedKvFault(
            "injected transient KV publish fault"
        )
        self._stall_next = 0
        self._stall_s = 0.0
        self.set_calls = 0
        self.faults_injected = 0
        self.stalls_injected = 0

    # ----------------------------------------------------------------- writes
    def set(self, key: str, value: bytes) -> None:
        """Publish one key (at-least-once producer side; overwrite is legal)."""
        stall = 0.0
        with self._cond:
            if _SAN.enabled:
                _san_check(self, "_data,_fail_next,_stall_next")
            self.set_calls += 1
            if self._fail_next > 0:
                self._fail_next -= 1
                self.faults_injected += 1
                raise self._fail_exc()
            if self._stall_next > 0:
                self._stall_next -= 1
                self.stalls_injected += 1
                stall = self._stall_s
        if stall:
            # the stall simulates a slow child OUTSIDE the lock — a stalled
            # publisher must not block every other child's publish
            time.sleep(stall)
        with self._cond:
            self._data[key] = bytes(value)
            self.janitor.note(key)
            self._cond.notify_all()

    def delete(self, key: str) -> None:
        with self._cond:
            self._data.pop(key, None)
            self.janitor.forget(key)

    # ------------------------------------------------------------------ reads
    def get(self, key: str) -> Optional[bytes]:
        with self._cond:
            return self._data.get(key)

    def scan(self, prefix: str) -> Dict[str, bytes]:
        """All current ``key -> value`` pairs under a prefix (snapshot copy)."""
        with self._cond:
            return {k: v for k, v in self._data.items() if k.startswith(prefix)}

    def keys(self, pattern: str = "*") -> List[str]:
        with self._cond:
            return sorted(k for k in self._data if fnmatch.fnmatch(k, pattern))

    def wait_until(
        self,
        predicate: Callable[[Dict[str, bytes]], bool],
        deadline_s: float,
        prefix: str = "",
    ) -> bool:
        """Block until ``predicate(snapshot)`` holds or the deadline expires.

        This is the fan-in deadline primitive: the parent waits for "every
        expected child has published" with a bound, and a timeout is a
        *degrade* signal (partial rollup), never an exception. ``prefix``
        narrows the snapshot the predicate sees (interface parity with the
        coordination-service transport, whose scans are prefix-directed).
        """
        deadline = time.monotonic() + max(0.0, float(deadline_s))
        with self._cond:
            while True:
                snapshot = {
                    k: v for k, v in self._data.items() if k.startswith(prefix)
                } if prefix else dict(self._data)
                if predicate(snapshot):
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)  # lint-ok: R8 Condition.wait releases the lock while blocking

    # ---------------------------------------------------------------- hygiene
    def sweep_expired(self, now: Optional[float] = None) -> List[str]:
        """TTL-reap orphaned keys (dead children, abandoned epochs)."""
        return self.janitor.sweep(self.delete, now=now)

    # ------------------------------------------------------------ chaos seams
    def fail_publishes(
        self, n: int, exc_factory: Optional[Callable[[], Exception]] = None
    ) -> None:
        """Arm the next ``n`` ``set`` calls to raise a transient fault."""
        with self._cond:
            self._fail_next = int(n)
            if exc_factory is not None:
                self._fail_exc = exc_factory

    def stall_publishes(self, n: int, seconds: float) -> None:
        """Arm the next ``n`` ``set`` calls to sleep ``seconds`` first."""
        with self._cond:
            self._stall_next = int(n)
            self._stall_s = float(seconds)


class CoordinationServiceKV:
    """Fleet KV over the JAX distributed coordination service.

    Requires ``jax.distributed.initialize()`` (the same precondition as the
    allgather KV fallback). ``wait_until`` polls ``key_value_dir_get`` —
    the coordination client has no watch primitive — at a bounded cadence,
    so a fan-in deadline costs at most ``poll_s``-granular wakeups.
    """

    def __init__(self, ttl_s: float = 300.0, poll_s: float = 0.05) -> None:
        from jax._src import distributed

        client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "CoordinationServiceKV needs jax.distributed.initialize() (no coordination client)"
            )
        self._client = client
        self.poll_s = float(poll_s)
        self.janitor = KvTtlJanitor(ttl_s=ttl_s)

    def set(self, key: str, value: bytes) -> None:
        self._client.key_value_set_bytes(key, bytes(value))
        self.janitor.note(key)

    def delete(self, key: str) -> None:
        try:
            self._client.key_value_delete(key)
        finally:
            self.janitor.forget(key)

    def get(self, key: str) -> Optional[bytes]:
        try:
            return bytes(self._client.blocking_key_value_get_bytes(key, 1))
        except Exception:  # noqa: BLE001 - absent key surfaces as a timeout error
            return None

    def scan(self, prefix: str) -> Dict[str, bytes]:
        try:
            pairs: List[Tuple[str, Any]] = self._client.key_value_dir_get_bytes(prefix)
        except Exception as err:  # noqa: BLE001 - transport fault, retryable upstream
            raise FleetTransportError(f"coordination-service scan failed: {err}") from err
        return {str(k): bytes(v) for k, v in pairs}

    def wait_until(
        self,
        predicate: Callable[[Dict[str, bytes]], bool],
        deadline_s: float,
        prefix: str = "",
    ) -> bool:
        deadline = time.monotonic() + max(0.0, float(deadline_s))
        while True:
            try:
                snapshot = self.scan(prefix) if prefix else {}
            except FleetTransportError:
                snapshot = {}
            if predicate(snapshot):
                return True
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            time.sleep(min(self.poll_s, remaining))

    def sweep_expired(self, now: Optional[float] = None) -> List[str]:
        return self.janitor.sweep(self.delete, now=now)
