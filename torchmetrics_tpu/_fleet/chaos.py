"""Fleet-scale chaos: composed faults against a 3-level in-process tree.

The serving chaos suite (``_serving/chaos.py``) proves one server's
degradation envelope; this suite proves the *aggregation tier's*: with
child kills/restarts, corrupt payloads, transient and exhausted KV
publish faults, stragglers past the fan-in deadline, and zombie replays
all composed against one edge -> region -> global tree, every fenced
epoch's global rollup must still equal the golden fold of exactly its
contributing children — no double-count, no corrupt fold, no stall.

Fault schedule is **deterministic by epoch** (not probabilistic): each
fault class fires at a known epoch against a known victim, so the
expected degradation ledger — and the flight-recorder dump set, exactly
one per fault event — is computable in the test, not eyeballed. Row
payloads are pre-drawn from one seeded ``numpy`` Generator, and the
harness tracks every row it feeds per ``(leaf, epoch)``; golden equality
is checked per epoch by replaying exactly ``root.folded_sources`` into a
fresh metric sequentially (the flat ``merge_state``-free fold the tree
must agree with).

Invariants asserted (mirrors ``FleetChaosResult.ok``):

1. **Golden equality per fenced epoch** — tree rollup == sequential
   replay of its contributing sources, every epoch, byte-tolerance.
2. **Exactly-once fold** — zombie replays and redeliveries are dropped
   (``duplicates_dropped`` > 0 proves the fence was exercised).
3. **Quarantine, don't poison** — the corrupted payload never folds; its
   sources are the only ones missing from the final rollup besides rows
   never published.
4. **Degrade, don't await** — rollups complete within the deadline with
   missing children recorded; stragglers fold late, bounded staleness.
5. **One flight dump per fault event** — dump count per ``fleet_*``
   degradation kind equals the degradation event count of that kind.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from torchmetrics_tpu._fleet.node import Rollup
from torchmetrics_tpu._fleet.transport import InProcessKV, contribution_prefix
from torchmetrics_tpu._fleet.tree import FleetTree
from torchmetrics_tpu._fleet.wire import decode_contribution
from torchmetrics_tpu._observability.events import BUS as _BUS
from torchmetrics_tpu._observability.flight import (
    arm_flight_recorder,
    disarm_flight_recorder,
)
from torchmetrics_tpu._observability.state import OBS as _OBS
from torchmetrics_tpu._observability.state import set_telemetry_enabled
from torchmetrics_tpu._resilience.policy import RetryPolicy

__all__ = ["FleetChaosSpec", "FleetChaosResult", "run_fleet_chaos"]

_FLEET_KINDS = ("fleet_partial", "fleet_corrupt", "fleet_publish_degraded")


@dataclass(frozen=True)
class FleetChaosSpec:
    """Deterministic fault schedule for one chaos run."""

    epochs: int = 10
    branching: Tuple[int, ...] = (4, 4)  # 3 levels: global -> 4 regions -> 16 edges
    rows_per_epoch: int = 3
    deadline_s: float = 0.25  # per-level fan-in deadline
    epoch_window: int = 4
    seed: int = 1234
    # fault schedule: epoch index per fault class (None disables the fault)
    kill_epoch: Optional[int] = 1  # victim leaf down (restarts next epoch)
    zombie_capture_epoch: int = 2  # clean epoch whose payload gets replayed
    corrupt_epoch: Optional[int] = 3  # victim payload bit-flipped in the KV
    publish_fail_epoch: Optional[int] = 5  # victim's retries exhausted
    transient_fault_epoch: Optional[int] = 6  # single fault; retry recovers
    straggler_epoch: Optional[int] = 7  # victim publish stalls past deadline
    zombie_epoch: Optional[int] = 8  # captured payload replayed (fence test)
    stall_s: float = 0.0  # 0 -> 4x the deadline
    drain_epochs: int = 2  # extra clean epochs to fold late arrivals
    staleness_budget_ms: float = 60_000.0
    wallclock_budget_s: float = 120.0
    flight_dir: Optional[str] = None  # armed recorder's dump directory
    rtol: float = 1e-5
    atol: float = 1e-6

    def __post_init__(self) -> None:
        if self.epochs < 1:
            raise ValueError(f"epochs must be >= 1, got {self.epochs}")
        if len(self.branching) < 2:
            raise ValueError("fleet chaos needs >= 3 tree levels (branching of >= 2 fan-outs)")
        if self.rows_per_epoch < 1:
            raise ValueError(f"rows_per_epoch must be >= 1, got {self.rows_per_epoch}")
        if self.deadline_s <= 0:
            raise ValueError(f"deadline_s must be positive, got {self.deadline_s}")
        for name in ("kill_epoch", "corrupt_epoch", "publish_fail_epoch",
                     "transient_fault_epoch", "straggler_epoch", "zombie_epoch"):
            e = getattr(self, name)
            if e is not None and not (0 <= e < self.epochs):
                raise ValueError(f"{name}={e} outside [0, {self.epochs})")
        if self.zombie_epoch is not None and not (
            0 <= self.zombie_capture_epoch < self.zombie_epoch
        ):
            raise ValueError("zombie_capture_epoch must precede zombie_epoch")

    @property
    def effective_stall_s(self) -> float:
        return self.stall_s if self.stall_s > 0 else 4.0 * self.deadline_s


@dataclass
class FleetChaosResult:
    """What one chaos run observed; ``ok`` is the acceptance verdict."""

    epochs_run: int = 0
    leaves: int = 0
    rows_fed: int = 0
    rollups: List[Rollup] = field(default_factory=list)
    partial_rollups: int = 0
    duplicates_dropped: int = 0
    corrupt_quarantined: int = 0
    late_folds: int = 0
    transient_recovered: int = 0
    publish_degraded: int = 0
    ttl_reaped: int = 0
    max_staleness_ms: float = 0.0
    golden_checks: int = 0
    golden_equal: bool = True
    lost_sources: Set[Tuple[str, int]] = field(default_factory=set)
    events_by_kind: Dict[str, int] = field(default_factory=dict)
    dumps_by_kind: Dict[str, int] = field(default_factory=dict)
    fault_events: int = 0  # chaos_fault bus publishes
    elapsed_s: float = 0.0
    within_budget: bool = True
    failures: List[str] = field(default_factory=list)

    @property
    def dumps_match_events(self) -> bool:
        return all(
            self.dumps_by_kind.get(kind, 0) == count
            for kind, count in self.events_by_kind.items()
        )

    @property
    def ok(self) -> bool:
        return not self.failures and self.golden_equal and self.within_budget

    def describe(self) -> str:
        verdict = "OK" if self.ok else "FAILED"
        return (
            f"fleet-chaos[{verdict}] epochs={self.epochs_run} leaves={self.leaves} "
            f"rows={self.rows_fed} partial={self.partial_rollups} "
            f"dup_dropped={self.duplicates_dropped} corrupt={self.corrupt_quarantined} "
            f"late={self.late_folds} staleness_max={self.max_staleness_ms:.1f}ms "
            f"golden={'equal' if self.golden_equal else 'DIVERGED'} "
            f"dumps={dict(sorted(self.dumps_by_kind.items()))} "
            f"elapsed={self.elapsed_s:.2f}s"
            + (f" failures={self.failures}" if self.failures else "")
        )


def _tree_leaves_np(value: Any) -> List[np.ndarray]:
    import jax

    return [np.asarray(leaf) for leaf in jax.tree_util.tree_leaves(value)]


def _fleet_event_counts(tree: FleetTree) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for node in tree.nodes.values():
        for ev in node.metric.resilience_report().events:
            if ev.kind.startswith("fleet_"):
                counts[ev.kind] = counts.get(ev.kind, 0) + 1
    return counts


def run_fleet_chaos(
    template: Any,
    make_update: Callable[[np.random.Generator], Tuple[Any, ...]],
    spec: Optional[FleetChaosSpec] = None,
) -> FleetChaosResult:
    """Run the composed fault schedule against a fresh tree; never raises
    for chaos-detected divergence (inspect ``result.failures``).

    ``make_update(rng)`` returns one positional-args tuple for
    ``template.update``. Telemetry is force-enabled for the duration (the
    degradation bus and flight recorder are part of what is under test)
    and restored afterwards; any previously-armed flight recorder is
    replaced by this run's.
    """
    spec = spec if spec is not None else FleetChaosSpec()
    result = FleetChaosResult()
    rng = np.random.default_rng(spec.seed)
    t_start = time.perf_counter()

    prev_enabled = _OBS.enabled
    set_telemetry_enabled(True)
    recorder = arm_flight_recorder(spec.flight_dir)
    kv = InProcessKV(ttl_s=3600.0)
    retry = RetryPolicy(max_retries=2, backoff_base=0.01, backoff_factor=2.0, backoff_max=0.05)
    tree = FleetTree.build(
        template,
        spec.branching,
        kv=kv,
        namespace="chaos",
        deadline_s=spec.deadline_s,
        retry=retry,
        epoch_window=spec.epoch_window,
    )
    leaves = tree.leaves
    result.leaves = len(leaves)
    victim = leaves[0]
    victim_region = victim.region

    # pre-draw every row up front: the schedule perturbs WHICH rows flow,
    # never their values, so two runs with one seed feed identical data
    rows: Dict[Tuple[str, int], List[Tuple[Any, ...]]] = {
        (leaf.node_id, e): [make_update(rng) for _ in range(spec.rows_per_epoch)]
        for e in range(spec.epochs)
        for leaf in leaves
    }
    fed: Set[Tuple[str, int]] = set()
    # two zombies probe two fences: the RECENT one (inside the sweep window)
    # must be dropped by the fold ledger; the STALE one (below the watermark)
    # is never even swept and must be reaped by the TTL janitor instead
    stale_zombie: Optional[Tuple[str, bytes]] = None
    recent_zombie: Optional[Tuple[str, bytes]] = None
    recent_capture_epoch = (
        max(spec.zombie_epoch - 2, 0) if spec.zombie_epoch is not None else None
    )
    stale_zombie_key: Optional[str] = None

    def _golden_check(epoch: int) -> None:
        """Root accumulator vs sequential replay of exactly its sources."""
        sources = set(tree.root.folded_sources)
        if not sources or tree.root.sources_truncated:
            return
        golden = template.clone()
        golden.reset()
        for src in sorted(sources):
            for args in rows.get(src, ()):
                golden.update(*args)
        result.golden_checks += 1
        got = _tree_leaves_np(tree.root.metric.compute())
        want = _tree_leaves_np(golden.compute())
        same = len(got) == len(want) and all(
            g.shape == w.shape and np.allclose(g, w, rtol=spec.rtol, atol=spec.atol)
            for g, w in zip(got, want)
        )
        if not same:
            result.golden_equal = False
            result.failures.append(
                f"epoch {epoch}: rollup diverged from golden fold of {len(sources)} sources"
            )

    try:
        for epoch in range(spec.epochs):
            dead: Set[str] = set()
            if spec.kill_epoch == epoch:
                dead.add(victim.node_id)
                _BUS.publish(
                    "chaos_fault", "FleetTree",
                    f"leaf kill: {victim.node_id} down for epoch {epoch}",
                    data={"seam": "fleet.publish", "fault": "leaf_kill", "epoch": epoch},
                )
                result.fault_events += 1

            # feed this epoch's rows to every live leaf
            for leaf in leaves:
                if leaf.node_id in dead:
                    continue  # a killed edge serves no traffic
                for args in rows[(leaf.node_id, epoch)]:
                    leaf.update(*args)
                    result.rows_fed += 1
                fed.add((leaf.node_id, epoch))

            # --- targeted publish faults fire against the victim FIRST,
            # synchronously, so the global fault injectors cannot leak onto
            # an unrelated concurrent publisher
            if spec.publish_fail_epoch == epoch:
                kv.fail_publishes(retry.attempts)
                assert not victim.publish(epoch)  # retries exhausted -> degraded
                result.publish_degraded += 1
                dead.add(victim.node_id)  # delta retained; no second publish
                _BUS.publish(
                    "chaos_fault", "FleetTree",
                    f"publish retries exhausted for {victim.node_id} at epoch {epoch}",
                    data={"seam": "fleet.publish", "fault": "publish_exhausted", "epoch": epoch},
                )
                result.fault_events += 1
            elif spec.transient_fault_epoch == epoch:
                kv.fail_publishes(1)
                if victim.publish(epoch):
                    result.transient_recovered += 1
                else:  # pragma: no cover - retry policy must absorb one fault
                    result.failures.append(f"epoch {epoch}: transient fault not absorbed by retry")
                dead.add(victim.node_id)
                result.fault_events += 1
                _BUS.publish(
                    "chaos_fault", "FleetTree",
                    f"transient KV fault absorbed by retry ({victim.node_id}, epoch {epoch})",
                    data={"seam": "fleet.publish", "fault": "kv_transient", "epoch": epoch},
                )
            elif spec.straggler_epoch == epoch:
                kv.stall_publishes(1, spec.effective_stall_s)
                victim.publish_async(epoch)  # grabs the armed stall
                dead.add(victim.node_id)  # skip the normal publish path
                _BUS.publish(
                    "chaos_fault", "FleetTree",
                    f"straggler: {victim.node_id} publish stalled "
                    f"{spec.effective_stall_s:.2f}s past the {spec.deadline_s:.2f}s deadline",
                    data={"seam": "fleet.rollup", "fault": "straggler", "epoch": epoch},
                )
                result.fault_events += 1

            # remaining leaves publish asynchronously (the production shape)
            for leaf in leaves:
                if leaf.node_id not in dead:
                    leaf.publish_async(epoch)

            # wait for the expected contributions to land (stalled/killed
            # victims excluded), then inject the on-the-wire faults
            live = [lf.node_id for lf in leaves if lf.node_id not in dead]
            kv.wait_until(
                lambda snap: all(
                    any(k.startswith(contribution_prefix("chaos", lid, epoch)) for k in snap)
                    for lid in live
                ),
                spec.deadline_s,
            )
            if spec.corrupt_epoch == epoch:
                prefix = contribution_prefix("chaos", victim.node_id, epoch)
                for key, blob in sorted(kv.scan(prefix).items()):
                    result.lost_sources.update(decode_contribution(blob).sources)
                    flipped = bytearray(blob)
                    flipped[-1] ^= 0xFF  # payload bit-flip: outer checksum must catch it
                    kv.set(key, bytes(flipped))
                    _BUS.publish(
                        "chaos_fault", "FleetTree",
                        f"payload corruption on the wire: {key}",
                        data={"seam": "fleet.fold", "fault": "corrupt_payload", "epoch": epoch},
                    )
                    result.fault_events += 1
                    break
            if spec.zombie_epoch is not None and epoch in (
                spec.zombie_capture_epoch,
                recent_capture_epoch,
            ):
                prefix = contribution_prefix("chaos", leaves[1].node_id, epoch)
                for key, blob in sorted(kv.scan(prefix).items()):
                    if epoch == spec.zombie_capture_epoch:
                        stale_zombie = (key, blob)
                    if epoch == recent_capture_epoch:
                        recent_zombie = (key, blob)
                    break
            if spec.zombie_epoch == epoch:
                for payload in (stale_zombie, recent_zombie):
                    if payload is None:
                        continue
                    key, blob = payload
                    kv.set(key, blob)  # at-least-once redelivery of a folded epoch
                    if payload is stale_zombie and spec.zombie_capture_epoch <= (
                        epoch - spec.epoch_window
                    ):
                        stale_zombie_key = key  # below the fence window: TTL's problem
                    _BUS.publish(
                        "chaos_fault", "FleetTree",
                        f"zombie replay of folded contribution {key}",
                        data={"seam": "fleet.fold", "fault": "zombie_replay", "epoch": epoch},
                    )
                    result.fault_events += 1

            # interior levels roll up bottom-up, then the root
            for level in reversed(tree.levels[1:-1]):
                for node in level:
                    rollup = node.rollup(epoch)
                    result.duplicates_dropped += rollup.duplicates_dropped
                    result.corrupt_quarantined += rollup.corrupt_quarantined
                    result.late_folds += rollup.late_arrivals
                    if rollup.partial:
                        result.partial_rollups += 1
                        if node.region != victim_region:
                            result.failures.append(
                                f"epoch {epoch}: unexpected partial rollup at {node.node_id}"
                            )
                    node.publish_async(epoch)
            root_rollup = tree.root.rollup(epoch)
            result.rollups.append(root_rollup)
            result.duplicates_dropped += root_rollup.duplicates_dropped
            result.corrupt_quarantined += root_rollup.corrupt_quarantined
            result.late_folds += root_rollup.late_arrivals
            if root_rollup.partial:
                result.partial_rollups += 1
            result.max_staleness_ms = max(result.max_staleness_ms, root_rollup.staleness_ms)
            result.epochs_run += 1
            _golden_check(epoch)

        # drain: land every in-flight publish, then clean epochs fold the
        # late arrivals (straggler + retained deltas) into the rollup
        tree.join_pending(timeout=2.0 * spec.effective_stall_s + 5.0)
        for extra in range(spec.drain_epochs):
            epoch = spec.epochs + extra
            for leaf in leaves:
                leaf.publish_async(epoch)
            for level in reversed(tree.levels[1:-1]):
                for node in level:
                    rollup = node.rollup(epoch)
                    result.duplicates_dropped += rollup.duplicates_dropped
                    result.late_folds += rollup.late_arrivals
                    node.publish_async(epoch)
            root_rollup = tree.root.rollup(epoch)
            result.rollups.append(root_rollup)
            result.late_folds += root_rollup.late_arrivals
            result.epochs_run += 1
            _golden_check(epoch)
        tree.join_pending(timeout=5.0)

        # the stale zombie (below every fence window) is the janitor's:
        # nothing sweeps its epoch anymore, TTL cleanup must reap it
        if stale_zombie_key is not None and kv.get(stale_zombie_key) is not None:
            reaped = kv.janitor.sweep(kv.delete, now=time.monotonic() + 7200.0)
            result.ttl_reaped = len(reaped)
            if stale_zombie_key not in reaped:
                result.failures.append("stale zombie contribution survived the TTL sweep")

        # every fed-and-published source must fold eventually, except the
        # quarantined payload's (data loss by design) and the killed epoch's
        expected = {
            src for src in fed if src not in result.lost_sources
        }
        folded = set(tree.root.folded_sources)
        missing = expected - folded
        if missing:
            result.failures.append(
                f"{len(missing)} published source(s) never folded: {sorted(missing)[:4]}..."
            )
        extra_folded = folded - expected
        if extra_folded:
            result.failures.append(
                f"rollup folded {len(extra_folded)} unexpected source(s) "
                f"(double count or quarantine leak): {sorted(extra_folded)[:4]}"
            )

        result.events_by_kind = _fleet_event_counts(tree)
        for dump in recorder.dumps():
            trig = dump.get("trigger", {})
            if trig.get("kind") == "degradation":
                kind = str(trig.get("data", {}).get("kind", ""))
                if kind.startswith("fleet_"):
                    result.dumps_by_kind[kind] = result.dumps_by_kind.get(kind, 0) + 1
        if not result.dumps_match_events:
            result.failures.append(
                f"flight dumps {result.dumps_by_kind} != degradation events {result.events_by_kind}"
            )
        if spec.zombie_epoch is not None and result.duplicates_dropped < 1:
            result.failures.append(
                "zombie replay within the fence window was not dropped as a duplicate"
            )
        if result.max_staleness_ms > spec.staleness_budget_ms:
            result.failures.append(
                f"rollup staleness {result.max_staleness_ms:.0f}ms exceeded the "
                f"{spec.staleness_budget_ms:.0f}ms budget"
            )
    finally:
        disarm_flight_recorder()
        set_telemetry_enabled(prev_enabled)

    result.elapsed_s = time.perf_counter() - t_start
    result.within_budget = result.elapsed_s <= spec.wallclock_budget_s
    if not result.within_budget:
        result.failures.append(
            f"chaos run took {result.elapsed_s:.1f}s > {spec.wallclock_budget_s:.1f}s budget"
        )
    return result
