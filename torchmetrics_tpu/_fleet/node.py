"""Aggregation node: one vertex of the edge -> region -> global rollup tree.

An :class:`AggregationNode` owns one metric *accumulator* (the cumulative
rollup of everything it has ever folded) and speaks two verbs:

- :meth:`AggregationNode.rollup` — sweep its children's contribution keys
  off the KV transport, fence out duplicates and zombies, quarantine
  corrupt payloads, and fold the survivors with the journaled merge
  operator (``Metric.merge_state``). The fan-in wait is **deadline
  bounded**: children missing at the deadline are *degraded, not
  awaited* — the rollup completes partial, stamped with exactly the
  contributing ``(child, epoch)`` set, and a ``fleet_partial``
  degradation event (which the flight recorder turns into a dump). A
  straggler's contribution is not lost: it folds into the *next* epoch's
  rollup as a late arrival.
- :meth:`AggregationNode.publish` — encode this node's *per-epoch delta*
  (everything folded since its last successful publish) as an
  integrity-checked wire contribution and push it to the parent's
  namespace under ``(node_id, epoch, state_digest)``, through
  ``run_guarded`` with the node's :class:`RetryPolicy` (bounded retries,
  exponential backoff, per-attempt watchdog). Exhausted retries degrade:
  the delta is *retained* and rides along with the next epoch's publish,
  so a flaky transport costs staleness, never data.

Delta semantics make the fencing story exact: each ``(leaf, epoch)``
delta enters the global accumulator at most once (the fold ledger drops
at-least-once redeliveries and zombie replays idempotently), so the root
rollup equals a flat sequential ``merge_state`` fold of precisely the
contributions it names in ``Rollup.sources``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from torchmetrics_tpu._fleet.observe import RegionLabeler
from torchmetrics_tpu._fleet.transport import contribution_key, contribution_prefix
from torchmetrics_tpu._fleet.wire import (
    Contribution,
    CorruptContribution,
    decode_contribution,
    encode_contribution,
)
from torchmetrics_tpu._observability import tracing as _obs_trace
from torchmetrics_tpu._observability.state import OBS as _OBS
from torchmetrics_tpu._observability.telemetry import telemetry_for as _telemetry_for
from torchmetrics_tpu._resilience.guard import SyncRetriesExhausted, run_guarded
from torchmetrics_tpu._resilience.policy import RetryPolicy
from torchmetrics_tpu.utilities.distributed import kv_key as _kv_key

__all__ = ["AggregationNode", "Rollup"]

# one shared default: fleet regions are bounded to top-K label slots
_DEFAULT_LABELER = RegionLabeler()


@dataclass(frozen=True)
class Rollup:
    """The receipt one :meth:`AggregationNode.rollup` call returns.

    ``contributing`` names exactly the ``(child, epoch)`` contributions
    folded THIS call (late arrivals from earlier epochs included);
    ``sources`` is their union of leaf-level provenance. ``partial`` is
    True iff at least one expected child missed the fan-in deadline.
    """

    node_id: str
    epoch: int
    contributing: Tuple[Tuple[str, int], ...]
    missing: Tuple[str, ...]
    sources: Tuple[Tuple[str, int], ...]
    partial: bool
    late_arrivals: int
    duplicates_dropped: int
    corrupt_quarantined: int
    staleness_ms: float
    latency_ms: float
    rows_folded: int = 0
    details: Tuple[str, ...] = field(default=())

    def describe(self) -> str:
        state = "partial" if self.partial else "full"
        return (
            f"rollup[{self.node_id} epoch={self.epoch} {state}] "
            f"folded={len(self.contributing)} missing={len(self.missing)} "
            f"late={self.late_arrivals} dup={self.duplicates_dropped} "
            f"corrupt={self.corrupt_quarantined} staleness={self.staleness_ms:.1f}ms"
        )


class AggregationNode:
    """One vertex of the fleet aggregation tree (leaf, region, or root).

    A leaf has no ``children``: its ``metric`` is the live edge metric the
    application updates, and :meth:`publish` ships the accumulated delta.
    An interior node's ``metric`` is the cumulative rollup of its subtree;
    :meth:`rollup` folds children, :meth:`publish` forwards the per-epoch
    delta upward. The root simply never publishes.

    Every node object is owned by exactly one driver thread; cross-node
    concurrency happens only through the (internally synchronized) KV
    transport, which is what keeps the fold single-writer and the
    fencing ledger race-free.
    """

    def __init__(
        self,
        node_id: str,
        template: Any,
        kv: Any,
        *,
        children: Sequence[str] = (),
        namespace: str = "default",
        region: Optional[str] = None,
        deadline_s: float = 2.0,
        retry: Optional[RetryPolicy] = None,
        epoch_window: int = 4,
        labeler: Optional[RegionLabeler] = None,
        sources_cap: int = 65536,
    ) -> None:
        if epoch_window < 1:
            raise ValueError(f"epoch_window must be >= 1, got {epoch_window}")
        self.node_id = str(node_id)
        self.children: Tuple[str, ...] = tuple(str(c) for c in children)
        self.kv = kv
        self.namespace = str(namespace)
        self.region = str(region) if region is not None else self.node_id
        self.deadline_s = float(deadline_s)
        self.retry = retry if retry is not None else RetryPolicy(max_retries=2, backoff_base=0.05, backoff_max=1.0)
        self.epoch_window = int(epoch_window)
        self._labeler = labeler if labeler is not None else _DEFAULT_LABELER
        # cumulative accumulator: everything this subtree ever folded
        self.metric = template.clone()
        self.metric.reset()
        self._template = template.clone()
        self._template.reset()
        # delta pending upward publish; survives failed publishes so
        # degraded epochs ride the next one. A publish SWAPS the pending
        # delta out (exclusive ownership while on the wire) and merges it
        # back only on retry exhaustion — so concurrent in-flight publishes
        # carry disjoint data and can never double-count a row upstream.
        # concurrency: _pending_* guarded-by _pub_lock (driver folds/preps
        # vs. async send threads merging back after a failed publish)
        self._pub_lock = threading.Lock()
        self._pending_delta = self._fresh_delta(template)
        self._pending_sources: Set[Tuple[str, int]] = set()
        self._pending_epochs: Set[int] = set()  # leaf provenance between publishes
        # epoch fence: (child, epoch) -> digest of the contribution folded.
        # Pruned below the watermark; anything at/below the watermark is a
        # zombie by definition (its epoch already aged out of the window).
        self._ledger: Dict[Tuple[str, int], str] = {}
        self._watermark = -1
        # full leaf provenance of the accumulator (golden-equality witness)
        self.folded_sources: Set[Tuple[str, int]] = set()
        self.sources_cap = int(sources_cap)
        self.sources_truncated = False
        self.last_rollup: Optional[Rollup] = None
        self.publish_failures = 0
        self._send_thread: Optional[threading.Thread] = None
        self._send_threads: List[threading.Thread] = []
        # per-fold scratch outputs read back by _rollup_inner
        self._last_fold_sources: Tuple[Tuple[str, int], ...] = ()
        self._last_fold_rows = 0
        self._last_fold_age_ms = 0.0

    def _fresh_delta(self, template: Optional[Any] = None) -> Any:
        delta = (template if template is not None else self._template).clone()
        delta.reset()
        return delta

    # ------------------------------------------------------------------ leaf
    def update(self, *args: Any, **kwargs: Any) -> None:
        """Convenience passthrough for leaves: update the live edge metric."""
        self.metric.update(*args, **kwargs)

    # ---------------------------------------------------------------- rollup
    def rollup(self, epoch: int) -> Rollup:
        """Fold this epoch's child contributions; degrade stragglers at the deadline."""
        epoch = int(epoch)
        t0 = time.perf_counter()
        span = None
        if _OBS.enabled and _OBS.tracing:
            span = _obs_trace.begin_span("fleet.rollup", self.node_id, epoch=epoch)
        try:
            result = self._rollup_inner(epoch, t0)
        except BaseException as err:
            if span is not None:
                _obs_trace.end_span(span, err)
                span = None
            raise
        finally:
            if span is not None:
                _obs_trace.end_span(span)
        self.last_rollup = result
        return result

    def _rollup_inner(self, epoch: int, t0: float) -> Rollup:
        prefixes = {c: contribution_prefix(self.namespace, c, epoch) for c in self.children}
        # the fan-in deadline: wait until every child has >= 1 key for THIS
        # epoch, or the clock runs out (degrade signal, not an error)
        if prefixes:
            common = _kv_key("fleet", self.namespace, "contrib") + "/"
            self.kv.wait_until(
                lambda snap: all(
                    any(k.startswith(p) for k in snap) for p in prefixes.values()
                ),
                self.deadline_s,
                prefix=common,
            )

        contributing: List[Tuple[str, int]] = []
        sources: Set[Tuple[str, int]] = set()
        details: List[str] = []
        late = duplicates = corrupt = 0
        rows = 0
        max_age_ms = 0.0
        floor = max(0, self._watermark + 1, epoch - self.epoch_window + 1)
        for child in self.children:
            for e in range(floor, epoch + 1):
                items = self.kv.scan(contribution_prefix(self.namespace, child, e))
                for key in sorted(items):
                    outcome = self._fold_one(child, e, key, items[key], details)
                    if outcome == "folded":
                        contributing.append((child, e))
                        contrib_sources = self._last_fold_sources
                        sources.update(contrib_sources)
                        rows += self._last_fold_rows
                        max_age_ms = max(max_age_ms, self._last_fold_age_ms)
                        if e < epoch:
                            late += 1
                    elif outcome == "duplicate":
                        duplicates += 1
                    elif outcome == "corrupt":
                        corrupt += 1

        missing = tuple(c for c in self.children if all(cc != c for cc, _ in contributing))
        partial = bool(missing)
        latency_ms = (time.perf_counter() - t0) * 1000.0

        # advance the fence: epochs at/below the new watermark are closed —
        # a zombie replaying one is dropped before decode from now on
        self._watermark = max(self._watermark, epoch - self.epoch_window)
        with self._pub_lock:
            for fence_key in [k for k in self._ledger if k[1] <= self._watermark]:
                del self._ledger[fence_key]

        if partial:
            self.metric._record_degradation(
                "fleet_partial",
                detail=(
                    f"node {self.node_id} epoch {epoch}: fan-in deadline "
                    f"{self.deadline_s:.2f}s expired with {len(missing)}/"
                    f"{len(self.children)} children missing ({', '.join(missing)}); "
                    f"folded {len(contributing)} contribution(s)"
                ),
            )
        if _OBS.enabled:
            telem = _telemetry_for(self.metric)
            label = self._labeler.note(self.region)
            outcome = "partial" if partial else "full"
            telem.inc(f"fleet_rollups|region={label}|outcome={outcome}")
            if contributing:
                telem.inc(f"fleet_contributions|region={label}", len(contributing))
            if late:
                telem.inc(f"fleet_late_arrivals|region={label}", late)
            if duplicates:
                telem.inc(f"fleet_duplicates_dropped|region={label}", duplicates)
            if corrupt:
                telem.inc(f"fleet_corrupt_quarantined|region={label}", corrupt)
            telem.set_gauge(f"fleet_rollup_staleness_ms|region={label}", max_age_ms)

        return Rollup(
            node_id=self.node_id,
            epoch=epoch,
            contributing=tuple(contributing),
            missing=missing,
            sources=tuple(sorted(sources)),
            partial=partial,
            late_arrivals=late,
            duplicates_dropped=duplicates,
            corrupt_quarantined=corrupt,
            staleness_ms=max_age_ms,
            latency_ms=latency_ms,
            rows_folded=rows,
            details=tuple(details),
        )

    def _fold_one(self, child: str, epoch: int, key: str, blob: bytes, details: List[str]) -> str:
        """Fence, verify, and fold one contribution key. Returns the outcome."""
        fence = (child, epoch)
        if epoch <= self._watermark or fence in self._ledger:
            # at-least-once redelivery or zombie replay: exactly-once fold
            # means everything after the first accepted payload is dropped
            self.kv.delete(key)
            details.append(f"dropped duplicate {key} (epoch fence)")
            return "duplicate"
        try:
            contrib = decode_contribution(blob)
            if contrib.node != child or contrib.epoch != epoch:
                raise CorruptContribution(
                    f"key/payload fence mismatch: key says ({child}, {epoch}), "
                    f"payload says ({contrib.node}, {contrib.epoch})"
                )
            if contrib.metric_class != type(self._template).__name__:
                raise CorruptContribution(
                    f"metric class mismatch: expected {type(self._template).__name__}, "
                    f"got {contrib.metric_class}"
                )
            scratch = self._verified_scratch(contrib)
        except CorruptContribution as err:
            self.kv.delete(key)
            details.append(f"quarantined {key}: {err}")
            self.metric._record_degradation(
                "fleet_corrupt",
                detail=f"node {self.node_id} quarantined contribution {key}: {err}",
            )
            return "corrupt"
        # a zero-count contribution is a liveness heartbeat: it counts
        # toward fan-in completeness but carries no rows, so it must leave
        # no provenance — otherwise idle epochs would pollute the
        # golden-equality witness with sources that folded nothing
        carried = contrib.count > 0
        self._last_fold_sources = contrib.sources if carried else ()
        self._last_fold_rows = contrib.count
        self._last_fold_age_ms = contrib.age_ms
        new_sources = set(contrib.sources) if carried else set()
        if carried:
            # fold into the cumulative accumulator first (driver-owned),
            # then into the pending delta headed upward (merge_state does
            # not mutate its argument, so one scratch serves both)
            self.metric.merge_state(scratch)
        with self._pub_lock:
            if carried:
                self._pending_delta.merge_state(scratch)
                self._pending_sources.update(new_sources)
            self._ledger[fence] = contrib.digest
        if carried:
            if len(self.folded_sources) + len(new_sources) <= self.sources_cap:
                self.folded_sources.update(new_sources)
            else:
                self.sources_truncated = True
        self.kv.delete(key)  # folded: reap the key (and its TTL record)
        return "folded"

    def _verified_scratch(self, contrib: Contribution) -> Any:
        """Load a contribution into a scratch clone, quarantining on repair.

        ``strict="repair"`` is deliberately run on a *scratch* metric: if
        the integrity pass repairs anything, the payload was corrupt, and a
        silently-repaired (defaulted) state must quarantine the whole
        contribution instead of folding a wrong value into the rollup.
        """
        scratch = self._template.clone()
        scratch.reset()
        scratch.__dict__["_resilience_events"] = []
        try:
            scratch.load_state_dict(dict(contrib.states), strict="repair")
        except Exception as err:  # noqa: BLE001 - any load failure is a quarantine
            raise CorruptContribution(f"state load failed: {type(err).__name__}: {err}") from err
        repaired = [
            ev for ev in scratch.__dict__.get("_resilience_events", ())
            if getattr(ev, "kind", "") == "state_repair"
        ]
        if repaired:
            raise CorruptContribution(
                f"integrity repair fired during load: {repaired[0].detail}"
            )
        scratch._update_count = contrib.count
        return scratch

    # --------------------------------------------------------------- publish
    def publish(self, epoch: int) -> bool:
        """Push this node's pending delta to the parent namespace; degrade on exhaustion.

        Returns True on success. On ``SyncRetriesExhausted`` the delta is
        merged back into the pending accumulator (it rides the next epoch's
        publish), a ``fleet_publish_degraded`` event is recorded, and False
        returns — the caller never sees the exception, because a failed
        publish is a staleness event, not a correctness event.
        """
        return self._send(self._prepare_publish(epoch))

    def publish_async(self, epoch: int) -> threading.Thread:
        """Like :meth:`publish`, but the (possibly stalling) wire send runs
        on a daemon thread. The delta swap-out happens synchronously on the
        caller's thread, so the live metric is free for the next epoch's
        updates the moment this returns — a straggling send costs
        staleness, never blocks the edge.
        """
        prepared = self._prepare_publish(epoch)
        self._send_thread = threading.Thread(
            target=self._send,
            args=(prepared,),
            name=f"fleet-publish-{self.node_id}-{prepared[2]}",
            daemon=True,
        )
        with self._pub_lock:
            self._send_threads.append(self._send_thread)
        self._send_thread.start()
        return self._send_thread

    def join_pending(self, timeout: Optional[float] = None) -> None:
        """Join outstanding async publish threads (drain / test teardown)."""
        with self._pub_lock:
            threads, self._send_threads = self._send_threads, []
        for t in threads:
            t.join(timeout)
        if self._send_thread is not None:
            self._send_thread.join(timeout)
            self._send_thread = None

    def _prepare_publish(self, epoch: int) -> Tuple[str, bytes, int, Any, Set[Tuple[str, int]]]:
        """Swap the pending delta out for exclusive wire ownership."""
        epoch = int(epoch)
        with self._pub_lock:
            if not self.children:
                # fold the live edge delta into the unACKed pending pile
                if self.metric._update_count > 0:
                    self._pending_delta.merge_state(self.metric)
                    self.metric.reset()
                self._pending_epochs.add(epoch)
                out_sources: Set[Tuple[str, int]] = {
                    (self.node_id, e) for e in self._pending_epochs
                }
            else:
                out_sources = set(self._pending_sources)
            outbound = self._pending_delta
            self._pending_delta = self._fresh_delta()
            self._pending_sources.clear()
            self._pending_epochs.clear()
        blob, digest = encode_contribution(
            outbound, self.node_id, epoch, tuple(sorted(out_sources))
        )
        key = contribution_key(self.namespace, self.node_id, epoch, digest)
        return key, blob, epoch, outbound, out_sources

    def _send(self, prepared: Tuple[str, bytes, int, Any, Set[Tuple[str, int]]]) -> bool:
        key, blob, epoch, outbound, out_sources = prepared
        telem = _telemetry_for(self.metric) if _OBS.enabled else None
        label = self._labeler.note(self.region) if telem is not None else ""

        def _attempt() -> None:
            if telem is not None:
                telem.inc(f"fleet_publish_attempts|region={label}")
            self.kv.set(key, blob)

        try:
            run_guarded(
                _attempt,
                self.retry,
                describe=f"fleet publish {self.node_id} epoch {epoch}",
            )
        except SyncRetriesExhausted as err:
            # merge the unACKed delta back: it rides the next publish
            with self._pub_lock:
                if outbound._update_count > 0:
                    self._pending_delta.merge_state(outbound)
                self._pending_sources.update(out_sources)
                self._pending_epochs.update(
                    e for n, e in out_sources if n == self.node_id
                )
                self.publish_failures += 1
            self.metric._record_degradation(
                "fleet_publish_degraded",
                detail=(
                    f"node {self.node_id} epoch {epoch}: publish exhausted "
                    f"{err.attempts} attempt(s) ({err.last_error}); delta retained "
                    f"for next epoch"
                ),
                attempts=err.attempts,
            )
            return False
        return True

    # ------------------------------------------------------------- lifecycle
    def step(self, epoch: int, *, publish: bool = True) -> Optional[Rollup]:
        """One epoch tick: interior nodes roll up, then (non-root) publish."""
        result = self.rollup(epoch) if self.children else None
        if publish:
            self.publish(epoch)
        return result
